"""Admission control: decide at the FRONT DOOR, on evidence.

Under overload, the worst policy is the default one — accept everything
and let deadlines die quietly in the queue.  Every queued request that
cannot possibly finish steals decode steps from requests that could
have.  This module makes the accept/reject decision explicit and cheap:

  * a BOUNDED queue — `queue_full` sheds instantly with a Retry-After
    derived from the measured drain rate, the 429 contract;
  * DEADLINE FEASIBILITY — from per-bucket prefill/step-time estimates
    (EWMAs fed by the engine's `serve.segment`/`serve.prefill` span
    measurements, observe/spans.py clock) the controller computes the
    earliest possible completion: queue wait + prefill + per-token decode.
    If that provably exceeds the request's deadline, admitting it would
    only manufacture a guaranteed timeout — reject as `infeasible`.
    No estimate yet = no proof = admit (the controller only rejects on
    evidence);
  * a MISS-RATE BREAKER — the resilience `CircuitBreaker` keyed on the
    windowed deadline-miss rate of completed requests.  Misses above the
    configured rate open it: new traffic is shed (or failed over to the
    degraded quantized bundle) for `reset_s`, then ONE probe request is
    admitted; an on-time probe closes the circuit.  This is the same
    closed/open/half-open machine PR 1 built for network endpoints, now
    protecting the decode engine from its own backlog.

Everything reads the injectable resilience clock, so admission tests run
on a `VirtualClock` with zero sleeps.
"""

from __future__ import annotations

import collections
import threading
from typing import Optional

from mmlspark_tpu.observe.metrics import inc_counter
from mmlspark_tpu.observe.trace import trace_event
from mmlspark_tpu.resilience.breaker import CLOSED, CircuitBreaker, \
    CircuitOpenError
from mmlspark_tpu.resilience.clock import Clock, get_clock
from mmlspark_tpu.serve.request import (BATCH, INTERACTIVE, PRIORITIES,
                                        Request)


class Overloaded(RuntimeError):
    """Shed at admission (HTTP 429): the engine cannot take this request
    now.  `reason` is one of 'queue_full' | 'infeasible' | 'breaker_open'
    | 'draining'; `retry_after_s` is the client's backoff hint."""

    def __init__(self, reason: str, retry_after_s: float = 1.0,
                 detail: str = ""):
        super().__init__(
            f"overloaded ({reason}): {detail or 'request shed at admission'}"
            f"; retry in {retry_after_s:.2f}s")
        self.reason = reason
        self.retry_after_s = max(0.0, float(retry_after_s))
        self.detail = detail


class InvalidRequest(ValueError):
    """A poison request (HTTP 400): malformed before any queueing —
    out-of-vocabulary tokens, empty prompt, a budget the model cannot
    hold.  Rejected without touching engine state."""


class StepTimeEstimator:
    """Per-bucket EWMA service-time model, fed by the engine's measured
    prefill and segment walls (the `observe` span clock).

    `service_s(bucket, n_tokens)` answers "how long would this request
    occupy the engine end to end" and returns None until a measurement
    for the bucket (or any bucket, as a coarse fallback) exists — the
    admission controller treats None as 'no proof, admit'.

    The feeds keep the model honest under the engine's fast paths: a
    CHUNKED prefill reports its summed chunk walls as one observation
    (the full prompt cost, not one slice), and a SPECULATIVE round
    reports round wall over tokens actually emitted per live row — so
    feasibility proofs track the measured speculative speedup, not the
    optimistic k+1 bound, and shrink admission back when acceptance
    drops."""

    def __init__(self, alpha: float = 0.3):
        self.alpha = float(alpha)
        self._prefill: dict[int, float] = {}   # bucket -> seconds
        self._step: dict[int, float] = {}      # bucket -> seconds / step
        self._handoff: dict[int, float] = {}   # bucket -> transfer seconds
        self._lock = threading.Lock()

    def _fold(self, table: dict, bucket: int, value: float) -> None:
        with self._lock:
            prev = table.get(bucket)
            table[bucket] = value if prev is None else \
                prev + self.alpha * (value - prev)

    def observe_prefill(self, bucket: int, seconds: float) -> None:
        self._fold(self._prefill, bucket, max(0.0, float(seconds)))

    def observe_step(self, bucket: int, seconds_per_step: float) -> None:
        self._fold(self._step, bucket, max(0.0, float(seconds_per_step)))

    def observe_handoff(self, bucket: int, seconds: float) -> None:
        """Disaggregated fleets: the measured prefill->decode KV transfer
        wall per bucket — a third priced stage, so admission feasibility
        on a tiered fleet includes the wire time between the tiers
        instead of pretending the cache teleports."""
        self._fold(self._handoff, bucket, max(0.0, float(seconds)))

    def _lookup(self, table: dict, bucket: int) -> Optional[float]:
        with self._lock:
            if bucket in table:
                return table[bucket]
            if table:
                # coarse fallback: the worst known bucket (admission must
                # never UNDER-estimate on a bucket it has not seen)
                return max(table.values())
            return None

    def step_s(self, bucket: int) -> Optional[float]:
        return self._lookup(self._step, bucket)

    def service_s(self, bucket: int, n_tokens: int) -> Optional[float]:
        """Estimated engine-occupancy seconds for one request — prefill
        stage + handoff stage (tiered fleets; 0 until observed) + decode
        steps — or None with no evidence yet."""
        step = self._lookup(self._step, bucket)
        if step is None:
            return None
        prefill = self._lookup(self._prefill, bucket) or 0.0
        handoff = self._lookup(self._handoff, bucket) or 0.0
        return prefill + handoff + step * max(1, int(n_tokens))

    def snapshot(self) -> dict:
        with self._lock:
            return {"prefill_s": dict(self._prefill),
                    "step_s": dict(self._step),
                    "handoff_s": dict(self._handoff)}


class MissRateBreaker:
    """Deadline-miss-rate keyed wrapper over the resilience breaker.

    Completions report through `record(missed=...)` into a sliding
    outcome window.  While CLOSED, the circuit opens only when the window
    holds at least `min_samples` outcomes and the miss fraction reaches
    `miss_rate` (threshold=1 on the inner breaker: the rate breach IS the
    failure).  While probing (half-open), the single admitted probe's own
    outcome decides: on-time closes and clears the window, a miss
    re-opens and restarts the cooldown — exactly the PR-1 state machine,
    with 'failure' redefined from 'connection refused' to 'deadline
    missed'."""

    def __init__(self, endpoint: str = "serve", *, window: int = 32,
                 min_samples: int = 8, miss_rate: float = 0.5,
                 reset_s: float = 5.0, clock: Optional[Clock] = None):
        if not 0.0 < miss_rate <= 1.0:
            raise ValueError(f"miss_rate must be in (0, 1], got {miss_rate}")
        self.endpoint = endpoint
        self.min_samples = int(min_samples)
        self.miss_rate = float(miss_rate)
        self._outcomes: collections.deque = collections.deque(
            maxlen=int(window))
        self._breaker = CircuitBreaker(endpoint, threshold=1,
                                       reset_s=reset_s, clock=clock)
        self._lock = threading.Lock()

    @property
    def state(self) -> str:
        return self._breaker.state

    def retry_in_s(self) -> float:
        return self._breaker.retry_in_s()

    def allow(self) -> None:
        """Gate one admission; raises CircuitOpenError when shedding."""
        self._breaker.allow()

    def record(self, missed: bool) -> None:
        with self._lock:
            if self._breaker.state != CLOSED:
                # probing: the probe's own outcome decides
                if missed:
                    self._breaker.record_failure(
                        DeadlineMissed(self.endpoint))
                else:
                    self._breaker.record_success()
                    self._outcomes.clear()
                return
            self._outcomes.append(bool(missed))
            n = len(self._outcomes)
            if n >= self.min_samples:
                rate = sum(self._outcomes) / n
                if rate >= self.miss_rate:
                    trace_event("serve.miss_rate_breach", cat="serve",
                                endpoint=self.endpoint,
                                rate=round(rate, 3), window=n)
                    self._breaker.record_failure(DeadlineMissed(
                        self.endpoint, rate=rate, window=n))
                    self._outcomes.clear()

    def miss_rate_now(self) -> float:
        with self._lock:
            n = len(self._outcomes)
            return sum(self._outcomes) / n if n else 0.0


class DeadlineMissed(RuntimeError):
    """The 'failure' fed to the breaker: a windowed miss-rate breach (or
    a missed probe)."""

    def __init__(self, endpoint: str, rate: float = 1.0, window: int = 1):
        super().__init__(
            f"deadline-miss rate {rate:.0%} over {window} completions "
            f"on {endpoint!r}")


class AdmissionController:
    """The bounded queue + the accept/shed decision (module docstring).

    `try_admit(request)` either appends the request to the queue and
    returns its lane ('primary' | 'degraded'), or raises `Overloaded`.
    The scheduler pops with `take(bucket, n)` / `pending()` and calls
    `close()` when draining — after which every admission sheds with
    reason 'draining'."""

    def __init__(self, capacity: int, estimator: StepTimeEstimator,
                 breaker: Optional[MissRateBreaker] = None, *,
                 max_batch: int = 1, degraded_available: bool = False,
                 batch_share: float = 1.0,
                 clock: Optional[Clock] = None):
        if capacity < 1:
            raise ValueError(f"queue capacity must be >= 1, got {capacity}")
        if not 0.0 < batch_share <= 1.0:
            raise ValueError(
                f"batch_share must be in (0, 1], got {batch_share}")
        self.capacity = int(capacity)
        self.estimator = estimator
        self.breaker = breaker
        self.max_batch = max(1, int(max_batch))
        self.degraded_available = bool(degraded_available)
        # weighted shedding: the batch lane may hold at most
        # ceil(capacity * batch_share) queue slots, and a full queue
        # displaces its NEWEST batch request to seat an interactive
        # arrival — overload costs the batch tier first, in both
        # directions (docs/serving.md "Prefix reuse & priority lanes")
        self.batch_share = float(batch_share)
        self._displaced: list[Request] = []
        # incrementally-maintained count of queued batch-lane requests:
        # interactive-only traffic (the common case) must not pay
        # per-call queue scans for lane bookkeeping it never uses
        self._batch_queued = 0
        self._clock = clock
        self._queue: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._closed = False
        # Retry-After hint while draining: the engine passes its drain
        # budget at close() so 429/503 responses advertise when a
        # replacement process could plausibly be serving again
        self.drain_hint_s = 1.0

    def _now(self) -> float:
        return (self._clock or get_clock()).monotonic()

    # -- scheduler side ---------------------------------------------------
    def close(self, retry_after_s: Optional[float] = None) -> None:
        """Stop admitting (graceful drain); queued requests stay queued —
        the drain loop decides their fate by deadline.  `retry_after_s`
        becomes the backoff hint shed traffic sees while draining."""
        if retry_after_s is not None:
            self.drain_hint_s = max(0.0, float(retry_after_s))
        self._closed = True

    def requeue(self, req: Request) -> None:
        """Put a request back at the HEAD of the queue: the router could
        not place it this tick (all replicas full or cooling down), or a
        failover retry is waiting for re-dispatch — arrival order must
        be preserved either way."""
        with self._lock:
            self._queue.appendleft(req)
            if getattr(req, "priority", INTERACTIVE) == BATCH:
                self._batch_queued += 1

    def remove(self, req: Request) -> bool:
        """Withdraw one still-queued request (a router cancelling the
        losing hedge attempt, or failing a dead replica's backlog over);
        True when it was found.  The caller owns finishing it."""
        with self._lock:
            try:
                self._queue.remove(req)
                if (self._batch_queued
                        and getattr(req, "priority",
                                    INTERACTIVE) == BATCH):
                    self._batch_queued -= 1
                return True
            except ValueError:
                return False

    def pending(self) -> int:
        with self._lock:
            return len(self._queue)

    def queued_tokens(self) -> int:
        with self._lock:
            return sum(r.max_new_tokens for r in self._queue)

    def take(self, bucket: int, n: int, lane: str = "primary") -> list:
        """Pop up to `n` queued requests for `bucket` on `lane`:
        interactive priority first, then batch, FIFO within each — a
        queued batch request never rides ahead of a waiting interactive
        one in its own bucket."""
        out: list[Request] = []
        with self._lock:
            if not self._batch_queued:
                # fast path: no batch work queued, lane order is plain
                # FIFO — one pass, no per-request priority reads
                keep: collections.deque = collections.deque()
                while self._queue and len(out) < n:
                    req = self._queue.popleft()
                    want = "degraded" if req.degraded else "primary"
                    if req.bucket == bucket and want == lane:
                        out.append(req)
                    else:
                        keep.append(req)
                keep.extend(self._queue)
                self._queue = keep
                return out
            for want_pri in PRIORITIES:
                if len(out) >= n:
                    break
                keep = collections.deque()
                while self._queue and len(out) < n:
                    req = self._queue.popleft()
                    want = "degraded" if req.degraded else "primary"
                    if (req.bucket == bucket and want == lane
                            and getattr(req, "priority",
                                        INTERACTIVE) == want_pri):
                        out.append(req)
                    else:
                        keep.append(req)
                keep.extend(self._queue)
                self._queue = keep
            self._batch_queued -= sum(
                1 for r in out
                if getattr(r, "priority", INTERACTIVE) == BATCH)
        return out

    def queued_buckets(self) -> list:
        """(bucket, lane) pairs with waiting work, FIFO-ordered by the
        head request of each pair."""
        seen: dict[tuple, None] = {}
        with self._lock:
            for req in self._queue:
                seen.setdefault(
                    (req.bucket, "degraded" if req.degraded else "primary"))
        return list(seen)

    def drain_displaced(self) -> list:
        """Collect batch requests a full queue displaced for interactive
        arrivals since the last call; the caller owns finishing them
        (the engine cancels them WITHOUT feeding the miss breaker — a
        displacement is a policy decision, not a deadline pathology)."""
        with self._lock:
            out, self._displaced = self._displaced, []
        return out

    def drop_expired(self, now: float) -> list:
        """Remove queued requests whose deadline already passed (they
        would be cancelled the moment they reached a group anyway);
        returns them for the engine to finish as timeouts."""
        expired: list[Request] = []
        with self._lock:
            alive = collections.deque()
            for req in self._queue:
                (expired if req.deadline <= now else alive).append(req)
            self._queue = alive
            if expired and self._batch_queued:
                self._batch_queued -= sum(
                    1 for r in expired
                    if getattr(r, "priority", INTERACTIVE) == BATCH)
        return expired

    # -- front-end side ---------------------------------------------------
    def _queue_wait_s(self, backlog_tokens: int) -> Optional[float]:
        """Earliest-start estimate for a new arrival: the backlog's decode
        steps over the engine's batch parallelism.  None without step
        evidence."""
        if backlog_tokens <= 0:
            return 0.0
        step = self.estimator.step_s(0)  # coarse: worst known bucket
        if step is None:
            return None
        return backlog_tokens * step / self.max_batch

    def try_admit(self, req: Request,
                  in_flight_tokens: int = 0) -> str:
        """Admit or shed (module docstring).  Returns the admitted lane;
        raises `Overloaded` otherwise.  `in_flight_tokens` is the
        scheduler's count of tokens still owed to resident requests —
        part of the backlog a feasibility proof must include."""
        now = self._now()
        if self._closed:
            inc_counter("serve.shed")
            trace_event("serve.shed", cat="serve", reason="draining",
                        request=req.id)
            raise Overloaded("draining", self.drain_hint_s,
                             "engine is draining")
        pri = getattr(req, "priority", INTERACTIVE)
        with self._lock:
            depth = len(self._queue)
            backlog = sum(r.max_new_tokens for r in self._queue)
            batch_depth = self._batch_queued
            # an interactive arrival's wait does not include queued BATCH
            # work — `take` serves it first, so pricing it against the
            # batch backlog would manufacture infeasible rejections for
            # exactly the traffic the lanes exist to protect
            backlog_ahead = (backlog if pri == BATCH or not batch_depth
                             else
                             sum(r.max_new_tokens for r in self._queue
                                 if getattr(r, "priority",
                                            INTERACTIVE) == INTERACTIVE))
            displaced = None
            if (depth >= self.capacity and pri == INTERACTIVE
                    and batch_depth):
                # weighted shedding, eviction side: a full queue seats an
                # interactive arrival by displacing its NEWEST queued
                # batch request (the engine finishes it as cancelled)
                for queued in reversed(self._queue):
                    if getattr(queued, "priority",
                               INTERACTIVE) == BATCH:
                        displaced = queued
                        break
                if displaced is not None:
                    self._queue.remove(displaced)
                    self._displaced.append(displaced)
                    self._batch_queued -= 1
                    backlog -= displaced.max_new_tokens
                    depth -= 1
        if displaced is not None:
            inc_counter("serve.displaced")
            trace_event("serve.displaced", cat="serve",
                        request=displaced.id, by=req.id)
        batch_cap = max(1, int(self.capacity * self.batch_share))
        if depth >= self.capacity or (pri == BATCH
                                      and batch_depth >= batch_cap):
            # Retry-After derived from evidence, not a constant: the
            # backlog's estimated drain time, floored by the breaker's
            # own cooldown when it is open too
            wait = self._queue_wait_s(backlog + in_flight_tokens)
            hint = wait if wait is not None else 1.0
            if self.breaker is not None:
                hint = max(hint, self.breaker.retry_in_s())
            inc_counter("serve.shed")
            trace_event("serve.shed", cat="serve", reason="queue_full",
                        request=req.id, depth=depth, priority=pri)
            detail = (f"batch lane at share cap ({batch_depth}/"
                      f"{batch_cap})" if depth < self.capacity
                      else f"queue at capacity ({depth})")
            raise Overloaded("queue_full", hint, detail)
        # deadline feasibility: reject only on PROOF (estimates exist and
        # the earliest completion still lands past the deadline)
        service = self.estimator.service_s(req.bucket, req.max_new_tokens)
        wait = self._queue_wait_s(backlog_ahead + in_flight_tokens)
        if service is not None and wait is not None:
            earliest = now + wait + service
            if earliest > req.deadline:
                inc_counter("serve.shed")
                trace_event("serve.shed", cat="serve", reason="infeasible",
                            request=req.id, priority=pri,
                            needed_s=round(wait + service, 4),
                            budget_s=round(req.deadline - now, 4))
                raise Overloaded(
                    "infeasible", 0.0,
                    f"needs ~{wait + service:.3f}s but deadline is "
                    f"{req.deadline - now:.3f}s away")
        lane = "primary"
        if self.breaker is not None:
            try:
                self.breaker.allow()
            except CircuitOpenError as e:
                if not self.degraded_available:
                    inc_counter("serve.shed")
                    trace_event("serve.shed", cat="serve",
                                reason="breaker_open", request=req.id)
                    raise Overloaded("breaker_open", e.retry_in_s,
                                     "deadline-miss breaker open") from e
                lane = "degraded"
                req.degraded = True
                inc_counter("serve.degraded")
                trace_event("serve.degraded", cat="serve", request=req.id)
        with self._lock:
            if self._closed:
                raise Overloaded("draining", self.drain_hint_s,
                                 "engine is draining")
            self._queue.append(req)
            if pri == BATCH:
                self._batch_queued += 1
        inc_counter("serve.admitted")
        return lane
