"""The routing front tier: one admission door over N engine replicas.

A single `ServingEngine` is a single point of failure — one wedged
decode loop and the whole surface is down.  The `Router` owns the
bounded admission queue and distributes admitted requests across a fleet
of in-process `Replica` handles (serve/replica.py); the stdlib HTTP
front end (serve/http.py) sits in front of `Router.submit` exactly as it
does for a bare engine, because the router duck-types the engine's
serving surface.  The policies, in dispatch order:

  * POWER-OF-TWO-CHOICES — among routable replicas, sample two and take
    the one owing fewer tokens (resident + queued).  Near-least-loaded
    placement at O(1) cost, without the herding a strict argmin causes.
  * OUTLIER EJECTION — each replica carries a PR-1 `CircuitBreaker`
    (`serve.replica.<name>`): consecutive failed attempts — or an
    explicit breach (deadline-miss EWMA over the configured rate, or a
    busy-but-stuck hang past `hang_timeout_s`) — open it.  An ejected
    replica gets no traffic until the cooldown elapses; then ONE real
    request routes through the half-open gate as the PROBE, and its
    on-time completion re-admits the replica (miss evidence cleared).
  * FAILOVER UNDER A RETRY BUDGET — an attempt that dies (replica crash,
    hang ejection, engine error) is retried on another replica only
    while the token-bucket `RetryBudget` grants a token; when the bucket
    is dry the request is SHED with 429 + Retry-After instead of
    queue-looping.  A retried request RE-PREFILLS from scratch, so its
    final tokens stay byte-exact with the offline decode (greedy);
    streamed partials may repeat across the failover — the stream epoch
    bumps so readers can restart cleanly.
  * HEDGING (optional, off by default) — when a request's remaining
    deadline falls under `hedge_fraction` x its estimated service time
    and only one attempt is live, a duplicate attempt is placed on a
    second replica (budget token required); first completion wins, the
    loser is cancelled WITHOUT feeding any breaker.

Every decision lands as a `serve.route.*` trace event and in the
`routing` timeline of run_summary.json; per-replica breakers export
through the standard Prometheus surface.  All deadline/health math runs
on the injectable resilience clock — the drills
(scripts/router_drill.py) drive `_tick()` under a `VirtualClock` with
zero sleeps.
"""

from __future__ import annotations

import dataclasses
import random
import threading
from typing import Optional

import numpy as np

from mmlspark_tpu import config
from mmlspark_tpu.observe.logging import get_logger
from mmlspark_tpu.observe.metrics import inc_counter
from mmlspark_tpu.observe.telemetry import active_run
from mmlspark_tpu.observe.trace import (mint_context, tail_promote,
                                        trace_event)
from mmlspark_tpu.resilience.breaker import (CLOSED, OPEN, STATE_CODES,
                                             CircuitOpenError)
from mmlspark_tpu.resilience.clock import Clock, get_clock
from mmlspark_tpu.serve.admission import (AdmissionController,
                                          InvalidRequest, Overloaded,
                                          StepTimeEstimator)
from mmlspark_tpu.serve.engine import (CREATED, DRAINING, READY, STOPPED,
                                       SERVE_DEFAULT_DEADLINE_S,
                                       SERVE_DRAIN_TIMEOUT_S,
                                       SERVE_QUEUE_CAPACITY, ServeConfig,
                                       ServingEngine)
from mmlspark_tpu.serve.handoff import HandoffBus
from mmlspark_tpu.serve.prefix_cache import PrefixCache
from mmlspark_tpu.serve.replica import Replica, ReplicaUnavailable
from mmlspark_tpu.serve.request import (CANCELLED, HANDOFF, INTERACTIVE,
                                        OK, PRIORITIES, TIMEOUT)

SERVE_REPLICAS = config.register(
    "MMLSPARK_TPU_SERVE_REPLICAS", 2,
    "serving fleet: engine replicas behind the router", ptype=int)
SERVE_RETRY_BUDGET_CAP = config.register(
    "MMLSPARK_TPU_SERVE_RETRY_BUDGET_CAP", 8.0,
    "serving fleet: token-bucket capacity for failover retries/hedges; "
    "an empty bucket sheds failed requests (429) instead of retrying",
    ptype=float)
SERVE_RETRY_BUDGET_PER_S = config.register(
    "MMLSPARK_TPU_SERVE_RETRY_BUDGET_PER_S", 0.5,
    "serving fleet: retry-budget refill rate (tokens/second)",
    ptype=float)
SERVE_EJECT_FAILURES = config.register(
    "MMLSPARK_TPU_SERVE_EJECT_FAILURES", 3,
    "serving fleet: consecutive attempt failures that eject a replica "
    "(open its breaker)", ptype=int)
SERVE_EJECT_MISS_RATE = config.register(
    "MMLSPARK_TPU_SERVE_EJECT_MISS_RATE", 0.6,
    "serving fleet: deadline-miss EWMA at or above which a replica is "
    "ejected", ptype=float)
SERVE_PROBE_RESET_S = config.register(
    "MMLSPARK_TPU_SERVE_PROBE_RESET_S", 5.0,
    "serving fleet: ejection cooldown before one half-open probe "
    "request is routed to the replica", ptype=float)
SERVE_HANG_TIMEOUT_S = config.register(
    "MMLSPARK_TPU_SERVE_HANG_TIMEOUT_S", 10.0,
    "serving fleet: a replica busy but making no progress for this long "
    "is declared hung — ejected, its in-flight work failed over",
    ptype=float)
SERVE_HEDGE_FRACTION = config.register(
    "MMLSPARK_TPU_SERVE_HEDGE_FRACTION", 0.0,
    "serving fleet: hedge a request onto a second replica when its "
    "remaining deadline < fraction x estimated service time "
    "(0 disables hedging)", ptype=float)
SERVE_PREFILL_REPLICAS = config.register(
    "MMLSPARK_TPU_SERVE_PREFILL_REPLICAS", 0,
    "disaggregated fleet: prefill-tier replicas (0 = colocated fleet; "
    "set together with MMLSPARK_TPU_SERVE_DECODE_REPLICAS)", ptype=int)
SERVE_DECODE_REPLICAS = config.register(
    "MMLSPARK_TPU_SERVE_DECODE_REPLICAS", 0,
    "disaggregated fleet: decode-tier replicas (0 = colocated fleet)",
    ptype=int)
SERVE_HANDOFF_TIMEOUT_S = config.register(
    "MMLSPARK_TPU_SERVE_HANDOFF_TIMEOUT_S", 10.0,
    "disaggregated fleet: a KV transfer with no page/ack movement for "
    "this long (virtual seconds) is failed and the request re-prefills "
    "elsewhere", ptype=float)
SERVE_HANDOFF_PAGES_PER_TICK = config.register(
    "MMLSPARK_TPU_SERVE_HANDOFF_PAGES_PER_TICK", 4,
    "disaggregated fleet: KV pages pushed per transfer per router tick "
    "— the pipelining knob that overlaps transfer with prefill compute",
    ptype=int)
SERVE_PREFIX_AFFINITY = config.register(
    "MMLSPARK_TPU_SERVE_PREFIX_AFFINITY", True,
    "serving fleet: steer requests sharing a first cache chunk to the "
    "same replica (hash-of-prefix affinity) so radix prefix-cache hits "
    "concentrate instead of spreading; falls back to power-of-two-"
    "choices when the target is ejected.  Only active on colocated "
    "fleets whose engines enable MMLSPARK_TPU_SERVE_PREFIX_CACHE",
    ptype=bool)

# the router-only terminal status: a failed request the retry budget
# would not let us place again (HTTP 429 + Retry-After)
SHED = "shed"


@dataclasses.dataclass
class RouterConfig:
    """Knobs for one Router (docs/serving.md 'Replicated fleet').

    None fields fall back to their MMLSPARK_TPU_SERVE_* config vars at
    construction, the ServeConfig convention."""

    replicas: Optional[int] = None
    queue_capacity: Optional[int] = None
    default_deadline_s: Optional[float] = None
    drain_timeout_s: Optional[float] = None
    retry_budget_cap: Optional[float] = None
    retry_budget_per_s: Optional[float] = None
    eject_failures: Optional[int] = None
    eject_miss_rate: Optional[float] = None
    miss_min_samples: int = 4
    probe_reset_s: Optional[float] = None
    hang_timeout_s: Optional[float] = None
    hedge_fraction: Optional[float] = None
    miss_alpha: float = 0.2
    seed: int = 0
    # disaggregated tiers (docs/serving.md 'Disaggregated tiers'): both
    # counts > 0 makes build_fleet construct role=prefill/decode pools
    # with the KV handoff bus between them
    prefill_replicas: Optional[int] = None
    decode_replicas: Optional[int] = None
    handoff_timeout_s: Optional[float] = None
    handoff_pages_per_tick: Optional[int] = None
    # hash-of-prefix replica affinity (colocated prefix-cache fleets)
    prefix_affinity: Optional[bool] = None

    def __post_init__(self):
        read = lambda explicit, var, cast: cast(
            var.current() if explicit is None else explicit)
        self.replicas = read(self.replicas, SERVE_REPLICAS, int)
        self.queue_capacity = read(self.queue_capacity,
                                   SERVE_QUEUE_CAPACITY, int)
        self.default_deadline_s = read(self.default_deadline_s,
                                       SERVE_DEFAULT_DEADLINE_S, float)
        self.drain_timeout_s = read(self.drain_timeout_s,
                                    SERVE_DRAIN_TIMEOUT_S, float)
        self.retry_budget_cap = read(self.retry_budget_cap,
                                     SERVE_RETRY_BUDGET_CAP, float)
        self.retry_budget_per_s = read(self.retry_budget_per_s,
                                       SERVE_RETRY_BUDGET_PER_S, float)
        self.eject_failures = read(self.eject_failures,
                                   SERVE_EJECT_FAILURES, int)
        self.eject_miss_rate = read(self.eject_miss_rate,
                                    SERVE_EJECT_MISS_RATE, float)
        self.probe_reset_s = read(self.probe_reset_s,
                                  SERVE_PROBE_RESET_S, float)
        self.hang_timeout_s = read(self.hang_timeout_s,
                                   SERVE_HANG_TIMEOUT_S, float)
        self.hedge_fraction = read(self.hedge_fraction,
                                   SERVE_HEDGE_FRACTION, float)
        self.prefill_replicas = read(self.prefill_replicas,
                                     SERVE_PREFILL_REPLICAS, int)
        self.decode_replicas = read(self.decode_replicas,
                                    SERVE_DECODE_REPLICAS, int)
        self.handoff_timeout_s = read(self.handoff_timeout_s,
                                      SERVE_HANDOFF_TIMEOUT_S, float)
        self.handoff_pages_per_tick = read(self.handoff_pages_per_tick,
                                           SERVE_HANDOFF_PAGES_PER_TICK,
                                           int)
        self.prefix_affinity = read(self.prefix_affinity,
                                    SERVE_PREFIX_AFFINITY, bool)
        if (self.prefill_replicas > 0) != (self.decode_replicas > 0):
            raise ValueError(
                "a disaggregated fleet needs BOTH prefill_replicas and "
                "decode_replicas > 0 (or both 0 for colocated)")
        if self.prefill_replicas < 0 or self.decode_replicas < 0:
            raise ValueError("tier replica counts must be >= 0")
        if self.handoff_timeout_s <= 0:
            raise ValueError("handoff_timeout_s must be > 0")
        if self.handoff_pages_per_tick < 1:
            raise ValueError("handoff_pages_per_tick must be >= 1")
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if self.retry_budget_cap < 0:
            raise ValueError("retry_budget_cap must be >= 0")
        if not 0.0 < self.eject_miss_rate <= 1.0:
            raise ValueError("eject_miss_rate must be in (0, 1]")
        if self.hang_timeout_s <= 0:
            raise ValueError("hang_timeout_s must be > 0")
        if self.hedge_fraction < 0:
            raise ValueError("hedge_fraction must be >= 0")


class RetryBudget:
    """Token bucket over the resilience clock: `cap` tokens, refilled at
    `per_s`.  Every failover retry and every hedge costs one token;
    `try_take()` refusing is the signal to SHED instead of retry — the
    bound that keeps a failing fleet from amplifying its own load."""

    def __init__(self, cap: float, per_s: float,
                 clock: Optional[Clock] = None):
        self.cap = max(0.0, float(cap))
        self.per_s = max(0.0, float(per_s))
        self._clock = clock
        self._tokens = self.cap
        self._lock = threading.Lock()
        self._last = self._now()
        self.spent = 0

    def _now(self) -> float:
        return (self._clock or get_clock()).monotonic()

    def _refill(self, now: float) -> None:
        if self.per_s > 0 and now > self._last:
            self._tokens = min(self.cap,
                               self._tokens + (now - self._last) * self.per_s)
        self._last = now

    def try_take(self) -> bool:
        with self._lock:
            self._refill(self._now())
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.spent += 1
                return True
            return False

    def tokens_now(self) -> float:
        with self._lock:
            self._refill(self._now())
            return self._tokens

    def retry_after_s(self) -> float:
        """Seconds until a token will exist — the Retry-After hint for
        budget-shed traffic (evidence, not a constant)."""
        with self._lock:
            self._refill(self._now())
            if self._tokens >= 1.0:
                return 0.1
            if self.per_s <= 0:
                return 1.0
            return max(0.1, (1.0 - self._tokens) / self.per_s)


class RouterRequest:
    """One admitted FLEET request: the stable handle a client waits on
    while its engine-level ATTEMPTS fail over between replicas.  Mirrors
    the `Request` surface (finish/wait/stream_*) so serve/http.py and
    the admission controller treat both alike; `attempts` holds
    (replica_name, engine Request) pairs, newest last."""

    __slots__ = ("id", "prompt", "true_len", "bucket", "max_new_tokens",
                 "arrival", "deadline", "priority", "degraded", "tokens",
                 "status", "detail", "finished_at", "retry_after_s",
                 "attempts", "retries", "hedged", "span", "trace",
                 "_event", "_progress")

    def __init__(self, req_id: int, prompt: np.ndarray, bucket: int,
                 max_new_tokens: int, arrival: float, deadline: float,
                 priority: str = INTERACTIVE):
        self.id = req_id
        self.prompt = prompt
        self.true_len = int(prompt.shape[0])
        self.bucket = bucket
        self.max_new_tokens = int(max_new_tokens)
        self.arrival = float(arrival)
        self.deadline = float(deadline)
        self.priority = priority
        self.degraded = False
        self.tokens: list[int] = []
        self.status: Optional[str] = None
        self.detail: str = ""
        self.finished_at: Optional[float] = None
        self.retry_after_s = 0.0       # backoff hint when status == shed
        self.attempts: list[tuple] = []
        self.retries = 0
        self.hedged = False
        self.span = None
        self.trace = None    # TraceContext minted at admission; every
        #   attempt (failover, hedge) is a child of the SAME trace id
        self._event = threading.Event()
        self._progress = threading.Condition()

    @property
    def finished(self) -> bool:
        return self.status is not None

    def latency_s(self) -> Optional[float]:
        if self.finished_at is None:
            return None
        return self.finished_at - self.arrival

    def _notify(self) -> None:
        # attempt progress callbacks (engine scheduler thread) and the
        # router's own terminal transition both land here
        with self._progress:
            self._progress.notify_all()

    def finish(self, status: str, now: float, detail: str = "") -> None:
        if self.status is not None:
            return
        self.status = status
        self.detail = detail
        self.finished_at = now
        self._event.set()
        self._notify()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    # -- token streaming ---------------------------------------------------
    def stream_state(self) -> tuple:
        """(epoch, tokens-so-far, finished).  The epoch counts attempts:
        a failover bumps it, telling a streaming reader its partial
        output was from a dead attempt and the stream restarts (the
        byte-exactness caveat in docs/serving.md — the FINAL tokens are
        exact, streamed partials may repeat)."""
        atts = self.attempts
        epoch = max(0, len(atts) - 1)
        if self.finished:
            return epoch, list(self.tokens), True
        if atts:
            return epoch, list(atts[-1][1].tokens), False
        return epoch, [], False

    def stream_wait(self, epoch: int, cursor: int,
                    timeout: Optional[float] = None) -> bool:
        """Park until the stream moved past (epoch, cursor): new tokens,
        a restart, or the terminal status."""
        def moved() -> bool:
            e, toks, fin = self.stream_state()
            return e != epoch or len(toks) > cursor or fin
        with self._progress:
            if moved():
                return True
            self._progress.wait(timeout)
            return moved()


class Router:
    """Health-aware routing over a replica fleet (module docstring).

    Inline (tests, drills): construct, `warmup()`, then `submit` +
    `_tick()` under a VirtualClock — nothing sleeps.  Production:
    `serve/lifecycle.start_router` spawns the single scheduler thread
    (it ticks every replica serially; replicas are in-process handles,
    not processes) and `start_http` serves `submit` unchanged."""

    def __init__(self, replicas: list, cfg: Optional[RouterConfig] = None,
                 *, clock: Optional[Clock] = None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.cfg = cfg or RouterConfig()
        self._clock = clock
        self.replicas: list[Replica] = list(replicas)
        self._by_name = {r.name: r for r in self.replicas}
        if len(self._by_name) != len(self.replicas):
            raise ValueError("replica names must be unique")
        # disaggregated tiers: replica roles partition the fleet; a
        # tiered fleet dispatches to the PREFILL tier only and the
        # handoff bus moves finished KV rows to the decode tier
        self._prefill_reps = [r for r in self.replicas
                              if r.role == "prefill"]
        self._decode_reps = [r for r in self.replicas
                             if r.role == "decode"]
        if self._prefill_reps or self._decode_reps:
            colocated = [r for r in self.replicas
                         if r.role not in ("prefill", "decode")]
            if not self._prefill_reps or not self._decode_reps or colocated:
                raise ValueError(
                    "a disaggregated fleet needs at least one prefill and "
                    "one decode replica, and no colocated ones")
        self.tiered = bool(self._prefill_reps)
        # the fleet estimator: every replica's measured prefill/segment
        # walls tee into it, so admission feasibility reflects real
        # decode speed no matter which replica produced the evidence
        self.estimator = StepTimeEstimator()
        for r in self.replicas:
            r.adopt_estimator(self.estimator)
        self.admission = AdmissionController(
            self.cfg.queue_capacity, self.estimator, None,
            max_batch=sum(r.engine.cfg.max_batch for r in self.replicas),
            clock=clock)
        self.budget = RetryBudget(self.cfg.retry_budget_cap,
                                  self.cfg.retry_budget_per_s, clock=clock)
        # fleet-aware prefix affinity: same first cache chunk → same
        # replica, so shared prefixes concentrate their radix-cache hits
        # instead of spreading across the pool.  The router only STEERS
        # — correctness never depends on landing the affinity target,
        # so an ejected target just falls back to power-of-two-choices.
        # Tiered fleets dispatch to the prefill tier, which rejects the
        # pool outright (satellite-6), so affinity stays colocated-only.
        self._affinity = bool(self.cfg.prefix_affinity and not self.tiered
                              and any(r.engine.cfg.prefix_cache
                                      for r in self.replicas))
        self._affinity_pool = sorted(self.replicas, key=lambda r: r.name)
        self._affinity_chunk = self.replicas[0].engine.cfg.cache_chunk
        self._rng = random.Random(self.cfg.seed)
        self._live: list[RouterRequest] = []   # dispatched, not finished
        self._state = CREATED
        self._state_lock = threading.Lock()
        self._wake = threading.Condition()
        self._next_id = 0
        self._id_lock = threading.Lock()
        self._latencies: list[float] = []
        self._counts: dict[str, int] = {}
        self._counts_lock = threading.Lock()
        self._drain_deadline: Optional[float] = None
        self._thread = None            # set by lifecycle.start_router
        self._guard = None             # PreemptionGuard, set by lifecycle
        self._run = active_run()
        self.handoff: Optional[HandoffBus] = None
        if self.tiered:
            self.handoff = HandoffBus(
                self, timeout_s=self.cfg.handoff_timeout_s,
                pages_per_tick=self.cfg.handoff_pages_per_tick)
            for rep in self._prefill_reps:
                rep.engine.handoff_export = self.handoff.make_export(
                    rep.name)

    # -- lifecycle ---------------------------------------------------------
    def now(self) -> float:
        return (self._clock or get_clock()).monotonic()

    @property
    def state(self) -> str:
        return self._state

    @property
    def ready(self) -> bool:
        return self._state == READY

    @property
    def alive(self) -> bool:
        return self._state in (READY, DRAINING)

    def warmup(self) -> "Router":
        """Warm every replica's shape classes before readiness flips."""
        if self._state != CREATED:
            return self
        for r in self.replicas:
            r.engine.warmup()
        self._state = READY
        self._record_routing("ready",
                             replicas=[r.name for r in self.replicas])
        get_logger("serve").info(
            "router ready: %d replicas warm", len(self.replicas))
        return self

    def begin_drain(self, reason: str = "stop") -> None:
        """Stop admitting; dispatched requests finish or cancel by
        min(their deadline, now + drain_timeout), then every replica
        engine drains.  Idempotent; SIGTERM-handler safe."""
        with self._state_lock:
            if self._state not in (CREATED, READY):
                return
            self._state = DRAINING
            self._drain_deadline = self.now() + self.cfg.drain_timeout_s
        self.admission.close(self.cfg.drain_timeout_s)
        inc_counter("serve.drains")
        self._record_routing("drain_start", reason=reason,
                             in_flight=len(self._live),
                             queued=self.admission.pending())
        with self._wake:
            self._wake.notify_all()

    def _finish_drain(self) -> None:
        for r in self.replicas:
            try:
                r.engine.stop()     # inline: replicas share this thread
            except Exception as e:
                get_logger("serve").warning(
                    "replica %s failed to stop cleanly: %r", r.name, e)
        if self.handoff is not None:
            self.handoff.close()
        self._state = STOPPED
        self._record_routing("drain_end", counts=dict(self._counts))
        self._gauge_fleet()
        with self._wake:
            self._wake.notify_all()

    def stop(self, timeout: Optional[float] = None) -> None:
        """Graceful stop: drain, then join the loop thread (if any)."""
        self.begin_drain("stop")
        if self._thread is not None:
            self._thread.join(timeout if timeout is not None
                              else self.cfg.drain_timeout_s + 5.0)
        else:
            while self._state == DRAINING:
                self._tick()

    def retry_after_s(self) -> float:
        """Backoff hint for refused/cancelled traffic (the engine
        contract): remaining drain time while draining, the drain budget
        once stopped, else the soonest replica probe."""
        now = self.now()
        if self._state == DRAINING and self._drain_deadline is not None:
            return max(0.1, self._drain_deadline - now)
        if self._state == STOPPED:
            return max(0.1, self.cfg.drain_timeout_s)
        return self._probe_hint()

    def _probe_hint(self) -> float:
        """Soonest half-open probe across ejected replicas — when the
        fleet could plausibly take traffic again."""
        waits = [r.breaker.retry_in_s() for r in self.replicas
                 if r.breaker.state != CLOSED]
        return max(0.1, min(waits)) if waits else 0.1

    # -- submission --------------------------------------------------------
    def _new_id(self) -> int:
        with self._id_lock:
            self._next_id += 1
            return self._next_id

    def in_flight(self) -> int:
        return sum(1 for rr in list(self._live) if not rr.finished)

    def fleet_load_tokens(self) -> int:
        return sum(r.load_tokens() for r in self.replicas)

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               deadline_s: Optional[float] = None,
               priority: Optional[str] = None) -> RouterRequest:
        """Admit one request into the FLEET queue or raise
        (`InvalidRequest` / `Overloaded`); the scheduler places it on a
        replica at the next tick.  Shed reasons add `no_replica`: the
        whole fleet is ejected/faulted and not yet due a probe."""
        pri = INTERACTIVE if priority is None else str(priority)
        if pri not in PRIORITIES:
            inc_counter("serve.poison")
            raise InvalidRequest(
                f"priority must be one of {PRIORITIES}, got {pri!r}")
        if not self.alive:
            self._count("shed_draining")
            self._count("shed")
            self._record_routing("shed", reason="draining")
            raise Overloaded("draining", self.retry_after_s(),
                             f"router is {self._state}")
        primary = self.replicas[0].engine
        n_new = int(max_new_tokens if max_new_tokens is not None
                    else primary.cfg.max_new_tokens)
        arr = primary._validate(prompt, n_new)
        try:
            bucket = primary._engines["primary"].bucket_for(arr.size)
        except ValueError as e:
            inc_counter("serve.poison")
            raise InvalidRequest(str(e)) from e
        now = self.now()
        deadline = now + (float(deadline_s) if deadline_s is not None
                          else self.cfg.default_deadline_s)
        rr = RouterRequest(self._new_id(), arr, bucket, n_new, now, deadline,
                           priority=pri)
        # a tiered fleet needs BOTH tiers reachable: prefill to take the
        # dispatch, decode to take the handoff
        pools = ([self._prefill_reps, self._decode_reps] if self.tiered
                 else [self.replicas])
        if not all(any(r.routable() or r.probe_due() for r in pool)
                   for pool in pools):
            self._count("shed_no_replica")
            self._count("shed")
            self._record_routing("shed", reason="no_replica", request=rr.id)
            raise Overloaded("no_replica", self._probe_hint(),
                             "no routable replica in the fleet")
        try:
            self.admission.try_admit(rr, self.fleet_load_tokens())
        except Overloaded as e:
            self._count(f"shed_{e.reason}")
            self._count("shed")
            self._record_routing("shed", reason=e.reason, request=rr.id)
            raise
        finally:
            # an interactive arrival at a full queue displaces the
            # newest queued batch request (weighted shedding: overload
            # costs the batch lane first); finish the victim as SHED
            # with a retry hint so its client backs off and resubmits
            for d in self.admission.drain_displaced():
                self._count("displaced")
                self._record_routing("shed", reason="displaced",
                                     request=d.id)
                self._complete(d, SHED, "displaced by interactive arrival",
                               retry_after=self.retry_after_s())
        self._count("admitted")
        # mint the request's fleet-wide trace identity AT admission: the
        # `admit` event is the waterfall root observe/assemble.py joins
        # every downstream shard's records against
        rr.trace = mint_context()
        self._record_routing("admit", request=rr.id, priority=pri,
                             bucket=bucket, **self._trace_fields(rr))
        with self._wake:
            self._wake.notify_all()
        return rr

    # -- accounting --------------------------------------------------------
    def _count(self, name: str, n: int = 1) -> None:
        with self._counts_lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def _record_routing(self, event: str, **fields) -> None:
        if self._run is not None:
            self._run.record_routing({"event": event, **fields})
        trace_event(f"serve.route.{event}", cat="serve", **fields)
        inc_counter(f"serve.route.{event}")

    @staticmethod
    def _trace_fields(rr: RouterRequest) -> dict:
        """The trace join fields a routing event carries (empty when the
        request predates admission or tracing is off)."""
        t = rr.trace
        return {"trace": t.trace_id, "sampled": t.sampled} \
            if t is not None else {}

    def _complete(self, rr: RouterRequest, status: str, detail: str = "",
                  retry_after: Optional[float] = None) -> None:
        now = self.now()
        if retry_after is not None:
            rr.retry_after_s = max(0.1, float(retry_after))
        rr.finish(status, now, detail)
        self._count("finished")
        self._count(status)
        fields = dict(request=rr.id, status=status, priority=rr.priority,
                      latency_s=round(now - rr.arrival, 6),
                      retries=rr.retries, hedged=rr.hedged,
                      deadline_miss=bool(status == OK and now > rr.deadline),
                      **self._trace_fields(rr))
        # tail-based sampling: a head-unsampled request that finished
        # badly (or slow, or needed a retry/hedge) is promoted to full
        # waterfall detail — the bit itself never flips
        tail = tail_promote(rr.trace, status=status,
                            latency_s=now - rr.arrival,
                            hedged=rr.hedged, retries=rr.retries)
        if tail:
            fields["tail"] = tail
        self._record_routing("finish", **fields)
        if status == OK:
            self._latencies.append(now - rr.arrival)
            self._count("tokens_served", len(rr.tokens))
            if now > rr.deadline:
                self._count("deadline_miss")
            else:
                self._count("met_deadline")
                self._count("goodput_tokens", len(rr.tokens))
        elif status == TIMEOUT:
            self._count("deadline_miss")
        inc_counter(f"serve.route.{status}")

    # -- ejection / re-admission -------------------------------------------
    def _replica_failure(self, rep: Replica, exc: BaseException,
                         reason: str, force: bool = False) -> None:
        """Record one attempt failure against a replica's ejection
        breaker.  `force` opens it outright (crash/hang/miss-rate: the
        evidence is unambiguous).  An already-OPEN breaker is left alone
        — late failures from the same incident must not restart the
        probe cooldown."""
        before = rep.breaker.state
        if force:
            spins = 0
            while (rep.breaker.state != OPEN
                   and spins <= rep.breaker.threshold):
                rep.breaker.record_failure(exc)
                spins += 1
        elif rep.breaker.state != OPEN:
            rep.breaker.record_failure(exc)
        if rep.breaker.state == OPEN and before != OPEN:
            self._count("ejections")
            self._record_routing("eject", replica=rep.name, reason=reason,
                                 retry_in_s=round(
                                     rep.breaker.retry_in_s(), 3))

    def _eject(self, rep: Replica, reason: str) -> None:
        self._replica_failure(rep, RuntimeError(reason), reason, force=True)

    def _maybe_miss_eject(self, rep: Replica) -> None:
        if (rep.breaker.state == CLOSED
                and rep.miss_samples >= self.cfg.miss_min_samples
                and rep.miss_ewma >= self.cfg.eject_miss_rate):
            self._eject(rep, "miss_rate")

    def _probe_failed(self, rep: Replica, why: str) -> None:
        rep.probe = None
        self._replica_failure(rep, RuntimeError(why), "probe_failed")

    def _readmit(self, rep: Replica) -> None:
        rep.breaker.record_success()
        rep.reset_miss_ewma()
        self._count("readmissions")
        self._record_routing("readmit", replica=rep.name)

    # -- placement ---------------------------------------------------------
    def _pop_queued(self) -> Optional[RouterRequest]:
        for bucket, lane in self.admission.queued_buckets():
            got = self.admission.take(bucket, 1, lane)
            if got:
                return got[0]
        return None

    def _affinity_target(self, rr: Optional[RouterRequest]):
        """The replica this request's first cache chunk hashes to, or
        None when affinity is off / the prompt is shorter than one
        chunk.  Pool order is sorted-by-name, so the mapping is stable
        across router restarts and replica list permutations."""
        if rr is None or not self._affinity:
            return None
        if rr.true_len < self._affinity_chunk:
            return None
        key = PrefixCache.affinity_key(rr.prompt, self._affinity_chunk)
        return self._affinity_pool[int(key, 16) % len(self._affinity_pool)]

    def _candidates(self, rr: Optional[RouterRequest] = None) -> list:
        """Dispatch preference: a due probe first (re-admission must not
        starve behind healthy capacity), then the affinity target when
        its breaker allows it, then the p2c pick, then the remaining
        routable replicas by load."""
        pool = self._prefill_reps if self.tiered else self.replicas
        order: list[Replica] = []
        probes = [r for r in pool if r.probe_due()]
        if probes:
            order.append(probes[0])
        healthy = [r for r in pool if r.routable()]
        target = self._affinity_target(rr)
        if target is not None and target in healthy:
            self._count("affinity_routes")
            self._record_routing("affinity", request=rr.id,
                                 replica=target.name)
            order.append(target)
            order.extend(sorted((r for r in healthy if r is not target),
                                key=lambda r: r.load_tokens()))
            return order
        if target is not None:
            # the affinity target is ejected / faulted / full: fall back
            # to power-of-two-choices rather than queueing behind it
            self._count("affinity_fallback")
            self._record_routing("affinity_fallback", request=rr.id,
                                 replica=target.name)
        if len(healthy) >= 2:
            a, b = self._rng.sample(healthy, 2)
            pick = min((a, b), key=lambda r: r.load_tokens())
            order.append(pick)
            order.extend(sorted((r for r in healthy if r is not pick),
                                key=lambda r: r.load_tokens()))
        else:
            order.extend(healthy)
        return order

    def _try_dispatch(self, rr: RouterRequest, rep: Replica,
                      now: float) -> Optional[object]:
        probe = rep.probe_due()
        if probe:
            try:
                rep.breaker.allow()   # we are the single half-open probe
            except CircuitOpenError:
                return None
        try:
            att = rep.submit(rr.prompt, rr.max_new_tokens,
                             deadline_s=max(1e-3, rr.deadline - now),
                             priority=rr.priority,
                             trace=None if rr.trace is None else
                             rr.trace.child(attempt=len(rr.attempts) + 1))
        except (Overloaded, ReplicaUnavailable, InvalidRequest) as e:
            if probe:
                # the gate was opened for us; a refused probe is a
                # failed probe (re-open, restart the cooldown)
                self._probe_failed(rep, f"probe refused: {e}")
            elif isinstance(e, ReplicaUnavailable):
                self._replica_failure(rep, e, "dispatch",
                                      force=rep.faulted)
            # a plain Overloaded is backpressure, not failure evidence
            return None
        rep.routed += 1
        att.listener = rr._notify
        rr.attempts.append((rep.name, att))
        if probe:
            rep.probe = att
            self._count("probes")
        if rr not in self._live:
            self._live.append(rr)
        self._record_routing("dispatch", request=rr.id, replica=rep.name,
                             probe=probe, attempt=len(rr.attempts),
                             load=rep.load_tokens(),
                             **self._trace_fields(rr))
        if self._run is not None and len(rr.attempts) == 1:
            self._run.observe_hist("serve.queue_wait_s", now - rr.arrival)
        return att

    def _dispatch(self, now: float) -> bool:
        progressed = False
        for _ in range(self.admission.pending()):
            rr = self._pop_queued()
            if rr is None:
                break
            if rr.deadline <= now:
                self._complete(rr, TIMEOUT, "expired in queue")
                progressed = True
                continue
            placed = False
            for rep in self._candidates(rr):
                if self._try_dispatch(rr, rep, now) is not None:
                    placed = True
                    break
            if placed:
                progressed = True
            else:
                # nothing can take work right now (all full, ejected, or
                # cooling down); keep FIFO order and wait for the next
                # tick — deadlines bound the wait
                self.admission.requeue(rr)
                break
        return progressed

    # -- harvest / failover ------------------------------------------------
    def _rr_for_attempt(self, att) -> Optional[RouterRequest]:
        """The live fleet request owning one engine attempt (the handoff
        bus resolves the exported engine request back to its router
        request this way — engine requests carry no back-pointer)."""
        for rr in list(self._live):
            for _, a in rr.attempts:
                if a is att:
                    return rr
        return None

    def _handoff_failed(self, rr: RouterRequest, reason: str,
                        now: float) -> None:
        """A KV transfer died (torn page, stall, sender crash, no decode
        capacity): the prefill work is lost, so the request re-prefills
        elsewhere through the normal failover path — retry budget,
        re-queue at the head, byte-exact final output."""
        if rr.finished:
            return
        if rr in self._live:
            self._live.remove(rr)
        self._count("handoff_retries")
        self._record_routing("handoff_failed", request=rr.id,
                             reason=reason, **self._trace_fields(rr))
        self._failover(rr, now)

    def _failover(self, rr: RouterRequest, now: float) -> None:
        if rr.deadline <= now:
            self._complete(rr, TIMEOUT, "deadline passed before failover")
            return
        if not self.budget.try_take():
            self._count("shed_retry_budget")
            self._record_routing("shed", reason="retry_budget",
                                 request=rr.id)
            self._complete(rr, SHED, "retry budget exhausted",
                           retry_after=self.budget.retry_after_s())
            return
        rr.retries += 1
        self._count("retries")
        self._record_routing("failover", request=rr.id, retry=rr.retries,
                             **self._trace_fields(rr))
        # re-queue at the head: the retried attempt re-prefills from
        # scratch on whichever replica dispatch picks next tick (greedy
        # output stays byte-exact; the stream epoch bumps on dispatch)
        self.admission.requeue(rr)

    def _harvest(self, now: float) -> bool:
        progressed = False
        for rr in list(self._live):
            if rr.finished:
                self._live.remove(rr)
                continue
            atts = rr.attempts
            winner = None
            for name, att in atts:
                if att.status == OK:
                    winner = (name, att)
                    break
            if winner is not None:
                name, att = winner
                rep = self._by_name[name]
                for n2, a2 in atts:
                    if a2 is not att and not a2.finished:
                        # losing hedge: withdrawn without breaker/miss
                        # evidence — scheduling, not failure
                        self._by_name[n2].engine.cancel_request(
                            a2, "hedge superseded")
                rr.tokens = list(att.tokens)
                rr.degraded = att.degraded
                missed = now > rr.deadline
                if rep.probe is att:
                    rep.probe = None
                    if missed:
                        self._probe_failed(rep, "probe missed deadline")
                    else:
                        self._readmit(rep)
                else:
                    if rep.breaker.state == CLOSED:
                        rep.breaker.record_success()
                    rep.observe_completion(missed)
                    self._maybe_miss_eject(rep)
                rep.completed_ok += 1
                self._live.remove(rr)
                self._complete(rr, OK)
                progressed = True
                continue
            if any(att.status is None for _, att in atts):
                continue               # still running somewhere
            name, att = atts[-1]
            if att.status == HANDOFF:
                continue     # KV transfer in flight; the bus owns the
                #              outcome (splice, cancel, or re-prefill)
            rep = self._by_name[name]
            if att.status == TIMEOUT:
                if rep.probe is att:
                    self._probe_failed(rep, "probe missed deadline")
                else:
                    rep.observe_completion(True)
                    self._maybe_miss_eject(rep)
                self._live.remove(rr)
                self._complete(rr, TIMEOUT,
                               att.detail or "attempt timed out")
            else:                      # error / cancelled: fail it over
                if rep.probe is att:
                    self._probe_failed(rep, att.detail or att.status)
                else:
                    self._replica_failure(
                        rep, RuntimeError(att.detail or att.status),
                        att.status, force=rep.faulted)
                self._live.remove(rr)
                self._failover(rr, now)
            progressed = True
        return progressed

    # -- hedging -----------------------------------------------------------
    def _hedge(self, now: float) -> bool:
        if self.cfg.hedge_fraction <= 0 or self.tiered:
            # tiered fleets don't hedge: a duplicate prefill would also
            # duplicate the KV transfer — failover handles loss instead
            return False
        progressed = False
        for rr in list(self._live):
            if rr.finished or rr.hedged or not rr.attempts:
                continue
            live_atts = [(n, a) for n, a in rr.attempts if a.status is None]
            if len(live_atts) != 1:
                continue
            est = self.estimator.service_s(rr.bucket, rr.max_new_tokens)
            if est is None:
                continue
            remaining = rr.deadline - now
            if remaining <= 0 or remaining >= self.cfg.hedge_fraction * est:
                continue
            current = live_atts[0][0]
            targets = [r for r in self.replicas
                       if r.routable() and r.name != current]
            if not targets:
                continue
            # a hedge costs a budget token like any retry; mark hedged
            # either way so a dry bucket is consulted once per request
            rr.hedged = True
            if not self.budget.try_take():
                continue
            target = min(targets, key=lambda r: r.load_tokens())
            try:
                att = target.submit(rr.prompt, rr.max_new_tokens,
                                    deadline_s=remaining,
                                    priority=rr.priority,
                                    trace=None if rr.trace is None else
                                    rr.trace.child(
                                        attempt=len(rr.attempts) + 1))
            except (Overloaded, ReplicaUnavailable):
                continue
            target.routed += 1
            att.listener = rr._notify
            rr.attempts.append((target.name, att))
            self._count("hedges")
            self._record_routing("hedge", request=rr.id,
                                 replica=target.name,
                                 remaining_s=round(remaining, 4),
                                 **self._trace_fields(rr))
            progressed = True
        return progressed

    # -- the scheduler pass ------------------------------------------------
    def _tick(self) -> bool:
        """One router pass: health checks, expiry, dispatch, replica
        ticks, harvest/failover, hedging, drain.  Synchronous and
        sleep-free; the drills drive it under a VirtualClock."""
        if (self._guard is not None and self._guard.triggered
                and self._state == READY):
            self.begin_drain("sigterm")
        now = self.now()
        worked = False
        # 1a. crash detection: a crash is observable at the handle (the
        # process exited) — eject immediately even if the replica was
        # idle when it died, so the breaker owns re-admission and the
        # blackout shows up as an `eject` event, never silently
        for rep in self.replicas:
            if rep.crashed and rep.breaker.state == CLOSED:
                self._eject(rep, "crash")
                worked = True
        # 1b. hang detection: busy but not progressing for too long
        for rep in self.replicas:
            if (rep.busy() and rep.breaker.state == CLOSED
                    and now - rep.last_progress > self.cfg.hang_timeout_s):
                self._eject(rep, "hang")
                failed = rep.fail_inflight(
                    f"replica {rep.name} hung "
                    f"(no progress for {now - rep.last_progress:.1f}s)")
                self._record_routing("hang", replica=rep.name,
                                     failed_over=failed)
                worked = True
        # 2. expire queued requests whose deadline already passed
        for rr in self.admission.drop_expired(now):
            self._complete(rr, TIMEOUT, "expired in queue")
            worked = True
        # 3. drain-deadline enforcement: past it, cancel everything left
        if self._state == DRAINING and now >= (self._drain_deadline or 0):
            for rr in list(self._live):
                if not rr.finished:
                    for name, att in rr.attempts:
                        if not att.finished:
                            self._by_name[name].engine.cancel_request(
                                att, "drain timeout")
                    if self.handoff is not None and self.handoff.drop_for(rr):
                        self._record_routing("cancel", request=rr.id,
                                             reason="drain_timeout")
                    self._complete(rr, CANCELLED, "drain timeout")
                self._live.remove(rr)
            for rr in self.admission.drop_expired(float("inf")):
                self._complete(rr, CANCELLED, "drain timeout")
            self._finish_drain()
            return True
        # 4. place queued work on replicas (probe first, then p2c)
        worked |= self._dispatch(now)
        # 5. advance every replica one scheduler pass
        prefill_worked = False
        for rep in self.replicas:
            if rep.tick():
                worked = True
                if rep.role == "prefill":
                    prefill_worked = True
        # 5b. pump the KV handoff bus: page pushes pipeline behind the
        # prefill tier's compute (the overlap the bench arm reports)
        if self.handoff is not None:
            worked |= self.handoff.pump(now, compute_worked=prefill_worked)
        # 6. harvest attempt outcomes; fail over the dead ones
        worked |= self._harvest(now)
        # 6b. per-replica SIGTERM drain: stop a draining replica's engine
        # once its own queue, residents, and (prefill tier) in-flight KV
        # transfers are empty — tier-correct drain semantics
        for rep in self.replicas:
            if rep.draining and rep.engine.state == DRAINING:
                owed = (self.handoff.transfers_from(rep.name)
                        if (self.handoff is not None
                            and rep.role == "prefill") else 0)
                if not rep.busy() and owed == 0:
                    rep.engine._finish_drain()
                    self._count("replica_drains")
                    self._record_routing("replica_drained",
                                         replica=rep.name, role=rep.role)
                    worked = True
        # 7. deadline-aware hedging (off unless configured)
        worked |= self._hedge(now)
        # 8. drain completion
        if (self._state == DRAINING and not self._live
                and self.admission.pending() == 0):
            self._finish_drain()
            return True
        if worked:
            self._gauge_fleet()
        return worked

    # -- the loop (spawned by serve/lifecycle.start_router) ----------------
    def _loop(self) -> None:
        while True:
            if (self._guard is not None and self._guard.triggered
                    and self._state == READY):
                self.begin_drain("sigterm")
            if self._state == STOPPED:
                return
            worked = self._tick()
            if self._state == STOPPED:
                return
            if not worked:
                with self._wake:
                    self._wake.wait(timeout=0.01)

    # -- stats -------------------------------------------------------------
    def _percentile(self, q: float) -> Optional[float]:
        if not self._latencies:
            return None
        return float(np.percentile(np.asarray(self._latencies), q))

    def stats(self) -> dict:
        """Fleet counts + latency percentiles + per-replica health — the
        dict /statz, the drills, and the bench arm read."""
        out = dict(self._counts)
        out["in_flight"] = self.in_flight()
        out["queued"] = self.admission.pending()
        out["state"] = self._state
        out["retry_budget_tokens"] = round(self.budget.tokens_now(), 3)
        out["retry_budget_spent"] = self.budget.spent
        for name, q in (("p50", 50), ("p95", 95), ("p99", 99)):
            p = self._percentile(q)
            out[f"latency_{name}_s"] = round(p, 6) if p is not None else None
        out["replicas"] = {r.name: r.health() for r in self.replicas}
        if self.tiered:
            def tier(reps):
                return {"replicas": [r.name for r in reps],
                        "routable": sum(1 for r in reps if r.routable()),
                        "draining": sum(1 for r in reps if r.draining),
                        "queued": sum(r.engine.admission.pending()
                                      for r in reps),
                        "in_flight": sum(r.engine.in_flight()
                                         for r in reps),
                        "load_tokens": sum(r.load_tokens() for r in reps)}
            out["tiers"] = {"prefill": tier(self._prefill_reps),
                            "decode": tier(self._decode_reps)}
            out["handoff"] = self.handoff.stats()
        return out

    def _gauge_fleet(self) -> None:
        if self._run is None:
            return
        self._run.gauge("serve.router.queue_depth", self.admission.pending())
        self._run.gauge("serve.router.in_flight", self.in_flight())
        self._run.gauge("serve.router.retry_budget_tokens",
                        self.budget.tokens_now())
        for r in self.replicas:
            self._run.gauge(f"serve.replica.{r.name}.load_tokens",
                            r.load_tokens())
            self._run.gauge(f"serve.replica.{r.name}.miss_ewma",
                            r.miss_ewma)
            self._run.gauge(f"serve.replica.{r.name}.breaker_state",
                            STATE_CODES[r.breaker.state])


def build_fleet(bundle, n: Optional[int] = None, *,
                cfg: Optional[RouterConfig] = None,
                serve_cfg: Optional[ServeConfig] = None,
                degraded_bundle=None,
                clock: Optional[Clock] = None) -> Router:
    """Construct a router over `n` fresh engine replicas of `bundle`
    (default: `cfg.replicas`).  Every replica shares the serve config
    and the degraded fallback bundle; each gets its own engine, breaker,
    and health state."""
    cfg = cfg or RouterConfig()
    scfg = serve_cfg or ServeConfig()

    def make(name: str, role_cfg: ServeConfig) -> Replica:
        engine = ServingEngine(bundle, role_cfg,
                               degraded_bundle=degraded_bundle, clock=clock)
        return Replica(name, engine, clock=clock,
                       eject_failures=cfg.eject_failures,
                       probe_reset_s=cfg.probe_reset_s,
                       miss_alpha=cfg.miss_alpha)

    replicas = []
    if cfg.prefill_replicas > 0:
        # disaggregated tiers: prefill pool p0..pN hands finished KV
        # rows over the bus to decode pool d0..dM
        for i in range(cfg.prefill_replicas):
            # a prefill-tier replica ships its finished KV rows over the
            # handoff bus — the prefix pool lives on the decode tier
            # only, never double-cached (ServeConfig rejects the combo)
            replicas.append(make(
                f"p{i}", dataclasses.replace(scfg, role="prefill",
                                             prefix_cache=False)))
        for i in range(cfg.decode_replicas):
            replicas.append(make(
                f"d{i}", dataclasses.replace(scfg, role="decode")))
    else:
        count = int(n if n is not None else cfg.replicas)
        for i in range(count):
            replicas.append(make(f"r{i}", scfg))
    return Router(replicas, cfg, clock=clock)
