"""The continuous-batching scheduler: robustness under load as the
design center.

One scheduler owns a set of resident decode GROUPS — one per (prompt
bucket, lane) — each a fixed-capacity batch driven through
`DecodeEngine`'s serve hooks (models/generate.py).  The loop advances in
SEGMENTS (`segment_steps` decode steps per compiled call) and makes every
robustness decision at the segment boundary, the natural synchronization
point the PR-3 engine already exposes:

  * JOIN — queued requests prefill as a cohort (padded to a power of two,
    so join batches reuse a handful of compiled shape classes) and their
    cache rows splice into free slots of the running batch
    (`merge_cache_rows`).  A short request that finishes frees its slot
    for the next arrival while long rows keep decoding: occupancy
    tracks offered load instead of draining to one.
  * CANCEL — a resident row whose deadline has passed is frozen (its
    `done` mask bit) and its request finished as `timeout`; the engine
    never spends another decode step on work nobody can use.
  * COMPLETE — rows that hit their token budget or stop token are
    harvested and their slots freed.

Overload never reaches this loop: admission (serve/admission.py) sheds at
the front door on queue depth, deadline feasibility, and the
deadline-miss breaker — and when the breaker is open with a quantized
fallback bundle configured, new traffic runs DEGRADED on the int8
weights (quant/) instead of being refused: reduced fidelity beats an
error page.

Every request carries a `serve.request` span; segments and prefills are
span-timed and feed the admission controller's per-bucket EWMAs, so the
feasibility math always reflects the engine as measured, not as hoped.
Deadline math runs on the injectable resilience clock — the whole
scheduler is testable with a `VirtualClock` and zero sleeps by calling
`_tick()` directly (the loop thread, spawned by serve/lifecycle.py, is
just `_tick` + a condition wait).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

import jax
import numpy as np

from mmlspark_tpu import config
from mmlspark_tpu.models.generate import (DEFAULT_CACHE_CHUNK, DecodeEngine,
                                          _round_up)
from mmlspark_tpu.observe.logging import get_logger
from mmlspark_tpu.observe.metrics import inc_counter
from mmlspark_tpu.observe.spans import monotonic
from mmlspark_tpu.observe.telemetry import active_run
from mmlspark_tpu.observe.trace import (mint_context, span_on_tracer,
                                        tail_promote, trace_event)
from mmlspark_tpu.resilience.clock import Clock, get_clock
from mmlspark_tpu.serve.admission import (AdmissionController,
                                          InvalidRequest, MissRateBreaker,
                                          Overloaded, StepTimeEstimator)
from mmlspark_tpu.serve.prefix_cache import PrefixCache
from mmlspark_tpu.serve.request import (CANCELLED, HANDOFF, INTERACTIVE,
                                        OK, PRIORITIES, TIMEOUT, Request)

SERVE_QUEUE_CAPACITY = config.register(
    "MMLSPARK_TPU_SERVE_QUEUE_CAPACITY", 64,
    "serving: bounded admission-queue depth; arrivals beyond it shed "
    "with Overloaded (429)", ptype=int)
SERVE_MAX_BATCH = config.register(
    "MMLSPARK_TPU_SERVE_MAX_BATCH", 8,
    "serving: resident decode slots per prompt-bucket group (the "
    "continuous batch width)", ptype=int)
SERVE_SEGMENT_STEPS = config.register(
    "MMLSPARK_TPU_SERVE_SEGMENT_STEPS", 8,
    "serving: decode steps per compiled segment — the join/cancel/"
    "complete boundary cadence", ptype=int)
SERVE_DEFAULT_DEADLINE_S = config.register(
    "MMLSPARK_TPU_SERVE_DEFAULT_DEADLINE_S", 30.0,
    "serving: deadline for requests that do not set one", ptype=float)
SERVE_DRAIN_TIMEOUT_S = config.register(
    "MMLSPARK_TPU_SERVE_DRAIN_TIMEOUT_S", 10.0,
    "serving: graceful-drain budget after SIGTERM/stop — in-flight "
    "requests finish or cancel by min(their deadline, this), then the "
    "loop exits", ptype=float)
SERVE_WARMUP_JOINS = config.register(
    "MMLSPARK_TPU_SERVE_WARMUP_JOINS", False,
    "serving: warmup also pre-compiles every late-join shape class "
    "(cohort merges and terminal segments at each grown cache width) — "
    "slower startup, but a ready engine then NEVER pays XLA against a "
    "deadline; recommended for production fleets", ptype=bool)
SERVE_PREFILL_CHUNK = config.register(
    "MMLSPARK_TPU_SERVE_PREFILL_CHUNK", 0,
    "serving: chunked prefill — join cohorts prefill in chunks of this "
    "many prompt tokens, ONE chunk per scheduler tick, so a long "
    "prompt's forward interleaves with resident decode segments instead "
    "of stalling them (0 = whole-prompt prefill; power of two "
    "recommended — buckets a non-divisor chunk doesn't divide fall back "
    "to whole-prompt)", ptype=int)
SERVE_SPEC_TOKENS = config.register(
    "MMLSPARK_TPU_SERVE_SPEC_TOKENS", 0,
    "serving: speculative decoding — draft-model tokens proposed per "
    "verify round (0 = off; needs a draft_bundle on the ServingEngine). "
    "Greedy outputs stay byte-identical to plain decoding; a round "
    "advances a row by up to this+1 tokens for one target forward",
    ptype=int)
SERVE_ROLE = config.register(
    "MMLSPARK_TPU_SERVE_ROLE", "colocated",
    "serving: this engine's tier in a disaggregated fleet — 'colocated' "
    "(prefill + decode on the same replica, the default), 'prefill' "
    "(runs chunked prefill only, ships finished KV cache rows to a "
    "decode replica over the handoff bus), or 'decode' (receives "
    "handed-off rows and decodes them to completion)", ptype=str)
SERVE_CACHE_DTYPE = config.register(
    "MMLSPARK_TPU_SERVE_CACHE_DTYPE", "model",
    "serving: resident KV-cache dtype — 'model' or 'int8' (per-head "
    "symmetric quantize-on-write; on a disaggregated fleet int8 pages "
    "also halve the handoff wire bytes)", ptype=str)

SERVE_PREFIX_CACHE = config.register(
    "MMLSPARK_TPU_SERVE_PREFIX_CACHE", False,
    "serving: cross-request radix prefix KV cache — finished prefill "
    "rows stay resident at cache_chunk granularity and later requests "
    "sharing a chunk-aligned prompt prefix splice them in, prefilling "
    "only the novel suffix (decode/colocated roles only; greedy outputs "
    "stay byte-identical at model dtype)", ptype=bool)
SERVE_PREFIX_MAX_ROWS = config.register(
    "MMLSPARK_TPU_SERVE_PREFIX_MAX_ROWS", 64,
    "serving: prefix-pool LRU budget in resident CHUNK rows (one row = "
    "one cache_chunk of KV slots); leased rows never evict", ptype=int)
SERVE_PREFIX_MAX_MB = config.register(
    "MMLSPARK_TPU_SERVE_PREFIX_MAX_MB", 256.0,
    "serving: prefix-pool LRU budget in resident megabytes (int8 KV "
    "rows fit ~4x more prefixes per MB than model-dtype)", ptype=float)
SERVE_LANE_BATCH_SHARE = config.register(
    "MMLSPARK_TPU_SERVE_LANE_BATCH_SHARE", 0.5,
    "serving: greatest fraction of the admission queue the BATCH "
    "priority lane may hold; beyond it batch arrivals shed queue_full "
    "while interactive traffic still seats (and a full queue displaces "
    "its newest batch request for an interactive arrival) — overload "
    "costs the batch tier first", ptype=float)

_ROLES = ("colocated", "prefill", "decode")


@dataclasses.dataclass
class ServeConfig:
    """Knobs for one ServingEngine (docs/serving.md 'Knobs').

    None fields fall back to their MMLSPARK_TPU_SERVE_* config vars at
    construction, the TrainerConfig convention."""

    max_new_tokens: int = 32          # engine-wide generation cap
    max_batch: Optional[int] = None   # resident slots per bucket group
    queue_capacity: Optional[int] = None
    segment_steps: Optional[int] = None
    default_deadline_s: Optional[float] = None
    drain_timeout_s: Optional[float] = None
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    stop_tokens: tuple = ()
    cache_chunk: int = DEFAULT_CACHE_CHUNK
    seed: int = 0
    # deadline-miss breaker (serve/admission.py MissRateBreaker)
    miss_window: int = 32
    miss_min_samples: int = 8
    shed_miss_rate: float = 0.5
    breaker_reset_s: float = 5.0
    warmup_buckets: tuple = ()        # () = the engine's smallest bucket
    warmup_joins: Optional[bool] = None  # pre-compile late-join shapes too
    prefill_chunk: Optional[int] = None  # chunked prefill (0 = off)
    spec_tokens: Optional[int] = None    # speculative draft depth (0 = off)
    role: Optional[str] = None           # colocated | prefill | decode
    cache_dtype: Optional[str] = None    # model | int8 resident KV cache
    prefix_cache: Optional[bool] = None  # cross-request prefix KV reuse
    prefix_max_rows: Optional[int] = None   # pool LRU budget, chunk rows
    prefix_max_mb: Optional[float] = None   # pool LRU budget, megabytes
    lane_batch_share: Optional[float] = None  # batch lane's queue share

    def __post_init__(self):
        read = lambda explicit, var, cast: cast(
            var.current() if explicit is None else explicit)
        self.max_batch = read(self.max_batch, SERVE_MAX_BATCH, int)
        self.role = read(self.role, SERVE_ROLE, str)
        self.cache_dtype = read(self.cache_dtype, SERVE_CACHE_DTYPE, str)
        self.queue_capacity = read(self.queue_capacity,
                                   SERVE_QUEUE_CAPACITY, int)
        self.segment_steps = read(self.segment_steps,
                                  SERVE_SEGMENT_STEPS, int)
        self.default_deadline_s = read(self.default_deadline_s,
                                       SERVE_DEFAULT_DEADLINE_S, float)
        self.drain_timeout_s = read(self.drain_timeout_s,
                                    SERVE_DRAIN_TIMEOUT_S, float)
        self.warmup_joins = read(self.warmup_joins,
                                 SERVE_WARMUP_JOINS, bool)
        self.prefill_chunk = read(self.prefill_chunk,
                                  SERVE_PREFILL_CHUNK, int)
        self.spec_tokens = read(self.spec_tokens, SERVE_SPEC_TOKENS, int)
        self.prefix_cache = read(self.prefix_cache,
                                 SERVE_PREFIX_CACHE, bool)
        self.prefix_max_rows = read(self.prefix_max_rows,
                                    SERVE_PREFIX_MAX_ROWS, int)
        self.prefix_max_mb = read(self.prefix_max_mb,
                                  SERVE_PREFIX_MAX_MB, float)
        self.lane_batch_share = read(self.lane_batch_share,
                                     SERVE_LANE_BATCH_SHARE, float)
        if self.prefix_max_rows < 1:
            raise ValueError("prefix_max_rows must be >= 1")
        if self.prefix_max_mb <= 0:
            raise ValueError("prefix_max_mb must be > 0")
        if not 0.0 < self.lane_batch_share <= 1.0:
            raise ValueError(
                f"lane_batch_share must be in (0, 1], "
                f"got {self.lane_batch_share}")
        if self.prefill_chunk < 0:
            raise ValueError("prefill_chunk must be >= 0")
        if self.spec_tokens < 0:
            raise ValueError("spec_tokens must be >= 0")
        if self.role not in _ROLES:
            raise ValueError(f"role must be one of {_ROLES}, "
                             f"got {self.role!r}")
        if self.cache_dtype not in ("model", "int8"):
            raise ValueError(
                f"cache_dtype must be 'model' or 'int8', "
                f"got {self.cache_dtype!r}")
        if self.role != "colocated" and self.spec_tokens:
            # the handoff carries target caches only; speculative lanes
            # would need the draft cache shipped too — out of scope
            raise ValueError(
                "speculative decoding is colocated-only: a "
                f"role={self.role!r} tier cannot run spec_tokens > 0")
        if self.role == "prefill" and self.prefix_cache:
            # disaggregated tiers must not double-cache: the pool lives
            # where decode does (build_fleet keeps it off the prefill
            # tier; its finished rows ship over the handoff bus and the
            # DECODE replica pools them)
            raise ValueError(
                "prefix_cache is decode/colocated-only: a role='prefill' "
                "replica ships finished KV rows over the handoff bus and "
                "must not keep a second resident copy — enable the pool "
                "on the decode tier instead")
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.segment_steps < 1:
            raise ValueError("segment_steps must be >= 1")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")


class _Group:
    """One (bucket, lane)'s resident batch: fixed `capacity` rows, numpy
    row state on the host, caches on the device.  A row is free when
    `rows[i] is None` (its `done` bit stays True so the compiled segment
    freezes it)."""

    def __init__(self, bucket: int, capacity: int):
        self.bucket = bucket
        self.capacity = capacity
        self.rows: list[Optional[Request]] = [None] * capacity
        self.caches = None
        self.draft_caches = None       # speculative lanes only
        self.spec_rounds = 0           # per-group RNG round counter
        self.reserved: set = set()     # slots held by in-flight chunked
        # prefills (their rows stay None until the cohort splices in)
        self.tok = np.zeros(capacity, np.int32)
        self.done = np.ones(capacity, bool)
        self.true_len = np.ones(capacity, np.int32)
        self.budget = np.zeros(capacity, np.int32)
        self.t_row = np.zeros(capacity, np.int32)
        self.row_ids = np.zeros(capacity, np.int32)
        # per-row sampling keys, cached until the row composition changes
        # (recomputing the fold every segment would retrace a vmap per
        # tick for nothing)
        self.keys = None
        self.keys_ids: Optional[tuple] = None

    def free_slots(self) -> list:
        return [i for i, r in enumerate(self.rows)
                if r is None and i not in self.reserved]

    def live_slots(self) -> list:
        return [i for i, r in enumerate(self.rows) if r is not None]

    def release(self, slot: int) -> None:
        self.rows[slot] = None
        self.done[slot] = True
        self.t_row[slot] = 0
        self.budget[slot] = 0
        self.true_len[slot] = 1


# engine lifecycle states
CREATED, READY, DRAINING, STOPPED = "created", "ready", "draining", "stopped"


def _assemble_prefix_row(chunks: list) -> list:
    """Concatenate a prefix hit's per-chunk pool payloads back into one
    cache row (slot axis 1), layer by layer — both cache layouts ride
    through (2-tuple model-dtype, 4-tuple int8 with its scale arrays)."""
    import jax.numpy as jnp
    row = []
    for layer_parts in zip(*chunks):
        row.append(tuple(jnp.concatenate(ts, axis=1)
                         for ts in zip(*layer_parts)))
    return row


class ServingEngine:
    """In-process serving over a model bundle (module docstring).

    Inline (tests, benches): construct, `warmup()`, then call `submit` +
    `_tick()` yourself — with an injected `VirtualClock` nothing sleeps.
    Production: `serve/lifecycle.start_engine(engine)` spawns the loop
    thread and wires SIGTERM -> `begin_drain`; `serve/lifecycle.
    start_http` puts the stdlib front end in front of `submit`.
    """

    def __init__(self, bundle, cfg: Optional[ServeConfig] = None, *,
                 degraded_bundle=None, draft_bundle=None,
                 clock: Optional[Clock] = None, mesh=None):
        self.cfg = cfg or ServeConfig()
        self._clock = clock
        self._bundle = bundle
        self._module = bundle.module()
        # serving over a device mesh: weights are placed once (replicated
        # at mp=1, partition-rule sharded when the mesh has a model axis)
        # and every DecodeEngine program traces its KV hints against it
        if mesh is not None and int(mesh.shape.get("seq", 1)) > 1:
            raise ValueError(
                "ServingEngine does not support a seq-sharded mesh "
                "(seq>1): continuous batching splices and pages "
                "whole-window cache rows, which a seq-partitioned "
                "window breaks up; use DecodeEngine.generate / "
                "TextGenerator for seq-parallel long-context decode")
        self._mesh = mesh
        # speculative lanes: one shared draft (zoo/speculative.py) drafts
        # for every lane — greedy exactness is per-lane by construction,
        # so the quantized degraded lane pairs with the same draft
        if self.cfg.spec_tokens and draft_bundle is None:
            raise ValueError(
                "spec_tokens > 0 needs a draft_bundle "
                "(zoo.truncated_draft_bundle builds one)")
        self._draft_module = (draft_bundle.module()
                              if self.cfg.spec_tokens else None)
        self._draft_vars = (self._place_replicated(draft_bundle)
                            if self.cfg.spec_tokens else None)
        self._engines = {"primary": self._decode_engine(self._module)}
        self._variables = {"primary": self._place_variables(bundle)}
        if degraded_bundle is not None:
            deg = degraded_bundle.module()
            if deg.vocab_size != self._module.vocab_size:
                raise ValueError(
                    "degraded bundle must share the primary vocabulary")
            self._engines["degraded"] = self._decode_engine(deg)
            self._variables["degraded"] = self._place_variables(
                degraded_bundle)
        self.estimator = StepTimeEstimator()
        self.breaker = MissRateBreaker(
            "serve", window=self.cfg.miss_window,
            min_samples=self.cfg.miss_min_samples,
            miss_rate=self.cfg.shed_miss_rate,
            reset_s=self.cfg.breaker_reset_s, clock=clock)
        self.admission = AdmissionController(
            self.cfg.queue_capacity, self.estimator, self.breaker,
            max_batch=self.cfg.max_batch,
            degraded_available=degraded_bundle is not None,
            batch_share=self.cfg.lane_batch_share, clock=clock)
        # cross-request prefix pool: primary-lane rows only (degraded
        # lanes decode different weights — their caches never mix)
        self._prefix = (PrefixCache(
            self.cfg.cache_chunk, max_rows=self.cfg.prefix_max_rows,
            max_bytes=int(self.cfg.prefix_max_mb * 2 ** 20))
            if self.cfg.prefix_cache else None)
        self._groups: dict[tuple, _Group] = {}
        # in-flight chunked prefills: one advances a single chunk per
        # tick, between phase 4 (joins) and phase 5 (segments)
        self._pending: list[dict] = []
        self.role = self.cfg.role
        # prefill tier: the handoff bus (serve/handoff.py) wires this to
        # receive each finished cohort's (reqs, first tokens, caches)
        # instead of seating them locally; the engine finishes the
        # exported requests with status `handoff`
        self.handoff_export = None
        self._state = CREATED
        self._state_lock = threading.Lock()
        self._wake = threading.Condition()
        self._next_id = 0
        self._id_lock = threading.Lock()
        self._latencies: list[float] = []
        self._counts: dict[str, int] = {}
        self._counts_lock = threading.Lock()
        self._drain_deadline: Optional[float] = None
        self._thread = None            # set by lifecycle.start_engine
        self._guard = None             # PreemptionGuard, set by lifecycle
        # telemetry handles captured ONCE, on the constructing thread
        # (the loop thread never sees the caller's contextvars)
        self._run = active_run()
        self._tracer = self._run.tracer if self._run is not None else None
        self._base_key = jax.random.key(self.cfg.seed)
        # jitted so repeated folds (every join) don't re-trace the vmap;
        # compiled once per cohort size
        self._fold_keys = jax.jit(jax.vmap(
            lambda i: jax.random.fold_in(self._base_key, i)))
        self._stops = np.asarray(self.cfg.stop_tokens or (), np.int32)

    def _decode_engine(self, module) -> DecodeEngine:
        return DecodeEngine(
            module, self.cfg.max_new_tokens,
            temperature=self.cfg.temperature, top_k=self.cfg.top_k,
            top_p=self.cfg.top_p, stop_tokens=self.cfg.stop_tokens,
            chunk=self.cfg.cache_chunk, mesh=self._mesh,
            cache_dtype=self.cfg.cache_dtype,
            prefill_chunk=self.cfg.prefill_chunk or None,
            draft_module=self._draft_module,
            spec_tokens=self.cfg.spec_tokens)

    def _place_replicated(self, bundle):
        """Draft weights replicate on any mesh (the draft is small; its
        cache rides the data axis only — parallel/partition.py
        DRAFT_KV_CACHE_SPEC)."""
        if self._mesh is None:
            return bundle.variables
        from mmlspark_tpu.parallel.bridge import replicate_tree
        return replicate_tree(bundle.variables, self._mesh)

    def _place_variables(self, bundle):
        """One-time weight placement for a lane: host tree off-mesh,
        replicated on a dp-only mesh, partition-rule sharded (the
        bundle's own rules, else DEFAULT_RULES) at mp >= 2."""
        if self._mesh is None:
            return bundle.variables
        if self._mesh.shape.get("model", 1) > 1:
            from mmlspark_tpu.parallel.partition import (
                UNMATCHED_REPLICATE, shard_tree)
            return shard_tree(bundle.variables, self._mesh,
                              bundle.partition_rules(),
                              on_unmatched=UNMATCHED_REPLICATE)
        from mmlspark_tpu.parallel.bridge import replicate_tree
        return replicate_tree(bundle.variables, self._mesh)

    # -- lifecycle ---------------------------------------------------------
    def now(self) -> float:
        return (self._clock or get_clock()).monotonic()

    @property
    def state(self) -> str:
        return self._state

    @property
    def ready(self) -> bool:
        return self._state == READY

    @property
    def alive(self) -> bool:
        return self._state in (READY, DRAINING)

    def warmup(self) -> "ServingEngine":
        """Pre-compile the serving shape classes BEFORE readiness flips:
        cohort prefills (each power-of-two join width up to capacity) and
        one resident segment per warmup bucket.  A first real request
        must never pay an XLA compile against its deadline."""
        if self._state != CREATED:
            return self
        engine = self._engines["primary"]
        buckets = tuple(self.cfg.warmup_buckets) or (engine.bucket_for(1),)
        t0 = monotonic()
        for lane, eng in self._engines.items():
            variables = self._variables[lane]
            for bucket in buckets:
                self._warm_bucket(eng, variables, int(bucket))
        self._record_serve({"event": "warmup_done",
                            "buckets": list(map(int, buckets)),
                            "seconds": round(monotonic() - t0, 3)})
        self._state = READY
        self._record_serve({"event": "ready"})
        get_logger("serve").info(
            "serving engine ready: buckets %s warmed in %.2fs",
            list(buckets), monotonic() - t0)
        return self

    def _warm_bucket(self, eng: DecodeEngine, variables, bucket: int) -> None:
        """Compile every shape class a full-budget batch in this bucket
        can touch: cohort prefills at each power-of-two join width, then
        a dummy capacity batch driven through the whole segment/window
        ladder — so a ready engine never pays XLA against a deadline.

        With `warmup_joins` the sweep also covers what the ladder alone
        cannot: the cohort-merge program at EVERY grown cache width the
        batch passes through (a late join splices a fresh base-width
        cohort into an old, wide batch) and the terminal segment class
        where the cache has already reached its final width — the
        shapes an engine otherwise compiles mid-flight, against a live
        request's deadline, the first time a join lands late."""
        cap = self.cfg.max_batch
        seg = self.cfg.segment_steps
        cohorts = {}
        chunks = eng.serve_prefill_chunks(bucket)
        n = 1
        while True:
            m = min(n, cap)
            prompts = np.zeros((m, bucket), np.int32)
            live = np.ones(m, bool)
            tl = np.ones(m, np.int32)
            keys = self._row_keys(np.arange(m))
            if chunks:
                # the chunked programs are what this bucket runs live
                state = None
                for ci in range(chunks):
                    state = eng.serve_prefill_chunk(variables, prompts,
                                                    tl, ci, state)
                tok, done, caches = eng.serve_prefill_finish(state, live,
                                                             keys)
            else:
                tok, done, caches = eng.serve_prefill(variables, prompts,
                                                      tl, live, keys)
            if eng.spec_tokens:
                dcaches = eng.serve_draft_prefill(self._draft_vars,
                                                  prompts)
            cohorts[m] = caches
            if n >= cap:
                break
            n *= 2
        if self.role == "prefill":
            # a prefill-tier engine never decodes or merges: the cohort
            # prefill programs above are its whole compiled surface
            return
        warmed_widths: set = set()

        def warm_joins(resident) -> None:
            # one merge program per (resident width, cohort width, join
            # count): splice k rows from the power-of-two cohort that a
            # k-wide join would prefill (engine._join pads the same way)
            width = int(resident[0][0].shape[1])
            if not self.cfg.warmup_joins or width in warmed_widths:
                return
            warmed_widths.add(width)
            for k in range(1, cap + 1):
                m = 1
                while m < k:
                    m *= 2
                DecodeEngine.merge_cache_rows(
                    resident, cohorts[min(m, cap)],
                    list(range(k)), list(range(k)), mesh=eng.mesh)

        budget = np.full(cap, self.cfg.max_new_tokens, np.int32)
        t_row = np.zeros(cap, np.int32)
        t = 0
        warm_joins(caches)
        if eng.spec_tokens:
            # speculative lanes replace segments with draft-verify
            # rounds: sweep the same window ladder at full-acceptance
            # stride, then pin the steady (window -> window) class that
            # partial acceptance revisits
            k1 = eng.spec_tokens + 1
            rounds = 0
            while t < self.cfg.max_new_tokens + k1:
                tr = np.minimum(t_row + t, self.cfg.max_new_tokens - 1)
                window = eng.serve_window(bucket, int(tr.max()), k1)
                (caches, dcaches, _, _, tok, done,
                 _) = eng.serve_spec_round(
                    variables, self._draft_vars, caches, dcaches, tok,
                    done, tl, budget, bucket, tr, rounds, keys, window)
                t += k1
                rounds += 1
            return
        while t < self.cfg.max_new_tokens:
            window = eng.serve_window(bucket, t, seg)
            caches, _, tok, done = eng.serve_step(
                variables, caches, tok, done, tl, budget, bucket, t_row,
                keys, seg, window)
            t += seg
            t_row = t_row + seg
            warm_joins(caches)
        if self.cfg.warmup_joins:
            # terminal class: the widest window a live row can demand
            # (t_row = max_new - 1), entered with the cache already at
            # that width — the ladder stops one segment short of it
            final = eng.serve_window(bucket, self.cfg.max_new_tokens - 1,
                                     seg)
            for _ in range(2):  # (last-ladder-width -> final), then the
                # steady state (final -> final); re-runs are cache hits
                caches, _, tok, done = eng.serve_step(
                    variables, caches, tok, done, tl, budget, bucket,
                    t_row, keys, seg, final)
                warm_joins(caches)

    def begin_drain(self, reason: str = "stop") -> None:
        """Stop admitting; in-flight requests finish or cancel by
        min(their deadline, now + drain_timeout); then the loop exits.
        Idempotent; safe from any thread (SIGTERM handler included)."""
        with self._state_lock:
            if self._state not in (CREATED, READY):
                return
            self._state = DRAINING
            self._drain_deadline = self.now() + self.cfg.drain_timeout_s
        self.admission.close(self.cfg.drain_timeout_s)
        inc_counter("serve.drains")
        trace_event("serve.drain_start", cat="serve", reason=reason)
        self._record_serve({"event": "drain_start", "reason": reason,
                            "in_flight": self.in_flight(),
                            "queued": self.admission.pending()})
        get_logger("serve").warning(
            "serving engine draining (%s): %d in flight, %d queued",
            reason, self.in_flight(), self.admission.pending())
        with self._wake:
            self._wake.notify_all()

    def _finish_drain(self) -> None:
        self._state = STOPPED
        trace_event("serve.drain_end", cat="serve")
        self._record_serve({"event": "drain_end",
                            "counts": dict(self._counts)})
        self._gauge_stats()
        with self._wake:
            self._wake.notify_all()

    def stop(self, timeout: Optional[float] = None) -> None:
        """Graceful stop: drain, then join the loop thread (if any)."""
        self.begin_drain("stop")
        if self._thread is not None:
            self._thread.join(timeout if timeout is not None
                              else self.cfg.drain_timeout_s + 5.0)
        else:
            # inline engines drain synchronously (each tick makes
            # progress: joins, decode, or the drain-deadline cancel)
            while self._state == DRAINING:
                if self._drained():
                    self._finish_drain()
                    break
                self._tick()

    # -- submission --------------------------------------------------------
    def _new_id(self) -> int:
        with self._id_lock:
            self._next_id += 1
            return self._next_id

    def _validate(self, prompt, max_new_tokens: int) -> np.ndarray:
        try:
            arr = np.asarray(prompt, np.int32)
        except (TypeError, ValueError) as e:
            raise InvalidRequest(f"prompt is not a token array: {e}") from e
        if arr.ndim != 1 or arr.size < 1:
            raise InvalidRequest(
                f"prompt must be a non-empty 1-D token array, got shape "
                f"{arr.shape}")
        if arr.min() < 0 or arr.max() >= self._module.vocab_size:
            raise InvalidRequest(
                f"prompt tokens outside the vocabulary "
                f"[0, {self._module.vocab_size})")
        if not 1 <= int(max_new_tokens) <= self.cfg.max_new_tokens:
            raise InvalidRequest(
                f"max_new_tokens must be in [1, {self.cfg.max_new_tokens}],"
                f" got {max_new_tokens}")
        return arr

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               deadline_s: Optional[float] = None,
               priority: Optional[str] = None, trace=None) -> Request:
        """Admit one request or raise (`InvalidRequest` for poison,
        `Overloaded` when shed).  `priority` picks the admission lane
        ('interactive', the default, or 'batch' — weighted shedding
        costs the batch lane first under overload).  `trace` is an
        upstream TraceContext (the router's per-attempt child); a bare
        engine mints its own root and records the waterfall's `admit`
        event itself.  Returns the live `Request`; callers block on
        `request.wait()` or poll `request.finished`."""
        if not self.alive:
            self._count("shed_draining")
            self._count("shed")
            self._record_serve({"event": "shed", "reason": "draining"})
            raise Overloaded("draining", self.retry_after_s(),
                             f"engine is {self._state}")
        pri = str(priority) if priority is not None else INTERACTIVE
        if pri not in PRIORITIES:
            raise InvalidRequest(
                f"priority must be one of {PRIORITIES}, got {priority!r}")
        n_new = int(max_new_tokens if max_new_tokens is not None
                    else self.cfg.max_new_tokens)
        arr = self._validate(prompt, n_new)
        try:
            bucket = self._engines["primary"].bucket_for(arr.size)
        except ValueError as e:
            inc_counter("serve.poison")
            raise InvalidRequest(str(e)) from e
        now = self.now()
        deadline = now + (float(deadline_s) if deadline_s is not None
                          else self.cfg.default_deadline_s)
        req = Request(self._new_id(), arr, bucket, n_new, now, deadline,
                      priority=pri)
        try:
            self.admission.try_admit(req, self.in_flight_tokens())
        except Overloaded as e:
            self._count(f"shed_{e.reason}")
            self._count("shed")
            self._record_serve({"event": "shed", "reason": e.reason,
                               "request": req.id, "priority": pri})
            raise
        finally:
            # a full queue seats an interactive arrival by displacing
            # its newest queued BATCH request: finish the displaced ones
            # here, WITHOUT feeding the miss breaker (displacement is
            # weighted-shedding policy, not a deadline pathology)
            for d in self.admission.drain_displaced():
                d.finish(CANCELLED, now,
                         "displaced by interactive arrival")
                self._count("displaced")
                self._count("shed")
                self._record_serve({
                    "event": "shed", "reason": "displaced",
                    "request": d.id,
                    "priority": getattr(d, "priority", INTERACTIVE)})
        self._count("admitted")
        if req.degraded:
            self._count("degraded")
            self._record_serve({"event": "degraded", "request": req.id})
        if trace is not None:
            req.trace = trace
        else:
            # no router tier above: this engine IS the front door, so it
            # mints the root context and records the waterfall's `admit`
            req.trace = mint_context()
            if req.trace is not None:
                self._record_serve({"event": "admit", "request": req.id,
                                    "priority": pri, "bucket": bucket,
                                    "trace": req.trace.trace_id,
                                    "sampled": req.trace.sampled})
        if self._tracer is not None:
            req.span = self._tracer.span(
                "serve.request", cat="serve", request=req.id,
                bucket=bucket, prompt_len=arr.size, new_tokens=n_new,
                deadline_in_s=round(deadline - now, 4),
                **self._trace_fields(req))
        with self._wake:
            self._wake.notify_all()
        return req

    # -- accounting --------------------------------------------------------
    def _count(self, name: str, n: int = 1) -> None:
        # front-end threads (submit) and the loop thread both count;
        # the lock keeps read-modify-write updates from losing increments
        with self._counts_lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def _record_serve(self, event: dict) -> None:
        if self._run is not None:
            self._run.record_serve(event)

    @staticmethod
    def _trace_fields(req: Request) -> dict:
        """The trace join fields a serve event/span carries (empty for an
        untraced request) — observe/assemble.py groups on `trace`."""
        t = getattr(req, "trace", None)
        return {"trace": t.trace_id, "sampled": t.sampled,
                "attempt": t.attempt} if t is not None else {}

    def _record_prefix(self, event: dict) -> None:
        if self._run is not None:
            self._run.record_prefix(event)

    def _gauge_prefix(self) -> None:
        # mmlspark_tpu_prefix_{hit_rate,resident_rows,resident_bytes,
        # evictions} on the Prometheus surface (observe/export.py)
        if self._run is None or self._prefix is None:
            return
        s = self._prefix.stats()
        self._run.gauge("prefix.hit_rate", round(s["hit_rate"], 4))
        self._run.gauge("prefix.resident_rows", s["resident_rows"])
        self._run.gauge("prefix.resident_bytes", s["resident_bytes"])
        self._run.gauge("prefix.evictions", s["evictions"])

    def retry_after_s(self) -> float:
        """The live backoff hint for refused/cancelled traffic: remaining
        drain time while draining (a replacement process is that far
        away), the configured drain budget once stopped, and the
        breaker's own cooldown otherwise — never a bare constant."""
        now = self.now()
        if self._state == DRAINING and self._drain_deadline is not None:
            return max(0.1, self._drain_deadline - now)
        if self._state == STOPPED:
            return max(0.1, self.cfg.drain_timeout_s)
        return max(0.1, self.breaker.retry_in_s())

    def cancel_request(self, req: Request, detail: str = "cancelled") -> bool:
        """Withdraw one unfinished request — resident row or still queued
        — WITHOUT feeding the miss breaker (the router cancelling a
        losing hedge attempt is scheduling, not engine failure).  True
        when the request was found and cancelled."""
        if req.finished:
            return False
        for g in list(self._groups.values()):
            for i in g.live_slots():
                if g.rows[i] is req:
                    req.finish(CANCELLED, self.now(), detail)
                    g.release(i)
                    self._count("cancelled_external")
                    return True
        for job in list(self._pending):
            if req in job["reqs"]:
                # its cohort row keeps prefilling (static shapes) but the
                # finish-time expiry filter drops it before the splice
                req.finish(CANCELLED, self.now(), detail)
                self._count("cancelled_external")
                return True
        if self.admission.remove(req):
            req.finish(CANCELLED, self.now(), detail)
            self._count("cancelled_external")
            return True
        return False

    def in_flight(self) -> int:
        # list() the dict: submit threads read while the loop thread
        # adds/drops groups (iterating the live dict would race);
        # chunked-prefill cohorts count too — they hold reserved slots
        return (sum(len(g.live_slots())
                    for g in list(self._groups.values()))
                + sum(len(job["reqs"]) for job in list(self._pending)))

    def in_flight_tokens(self) -> int:
        total = 0
        for g in list(self._groups.values()):
            for i in g.live_slots():
                req = g.rows[i]
                if req is not None:
                    total += max(0, req.max_new_tokens - len(req.tokens))
        for job in list(self._pending):
            for req in job["reqs"]:
                total += req.max_new_tokens
        return total

    def _row_keys(self, ids) -> jax.Array:
        return self._fold_keys(np.asarray(ids, np.int32))

    def _group_keys(self, g: _Group) -> jax.Array:
        ids = tuple(int(x) for x in g.row_ids)
        if g.keys_ids != ids:
            g.keys = self._row_keys(g.row_ids)
            g.keys_ids = ids
        return g.keys

    def _complete(self, req: Request, status: str, detail: str = "") -> None:
        now = self.now()
        req.finish(status, now, detail)
        missed = status != OK or now > req.deadline
        self.breaker.record(missed)
        # the per-request terminal record the strict-priority drill
        # asserts lane outcomes against (zero interactive misses while
        # batch sheds); gated so the no-telemetry hot path never builds
        # the dict
        if self._run is not None:
            rec = {
                "event": "finish", "request": req.id, "status": status,
                "priority": getattr(req, "priority", INTERACTIVE),
                "deadline_miss": bool(missed),
                "latency_s": round(now - req.arrival, 6),
                **self._trace_fields(req)}
            # tail-based sampling: a head-unsampled attempt that finished
            # badly or slow is promoted to full waterfall detail
            tail = tail_promote(getattr(req, "trace", None), status=status,
                                latency_s=now - req.arrival)
            if tail:
                rec["tail"] = tail
            self._record_serve(rec)
        self._count("finished")
        self._count(status)
        if status == OK:
            self._latencies.append(now - req.arrival)
            self._count("tokens_served", len(req.tokens))
            if now > req.deadline:
                self._count("deadline_miss")
                inc_counter("serve.deadline_miss")
                trace_event("serve.deadline_miss", cat="serve",
                            request=req.id,
                            late_s=round(now - req.deadline, 4))
            else:
                self._count("met_deadline")
                self._count("goodput_tokens", len(req.tokens))
        elif status == TIMEOUT:
            self._count("deadline_miss")
            inc_counter("serve.timeouts")
        inc_counter(f"serve.{status}")

    # -- the scheduler pass ------------------------------------------------
    def _tick(self) -> bool:
        """One scheduler pass: expire, join, advance every group one
        segment, harvest.  Returns True when any work was done (the loop
        idles on False).  Synchronous and sleep-free: tests drive it
        directly under a VirtualClock."""
        if (self._guard is not None and self._guard.triggered
                and self._state == READY):
            # SIGTERM arrived (PreemptionGuard flag): drain, never die
            # mid-decode — checked here as well as in the loop so inline
            # (threadless) engines honor the signal too
            self.begin_drain("sigterm")
        now = self.now()
        worked = False
        # 1. expire queued requests whose deadline already passed
        for req in self.admission.drop_expired(now):
            self._complete(req, TIMEOUT, "expired in queue")
            worked = True
        # 2. drain-deadline enforcement: past it, cancel everything left
        if self._state == DRAINING and now >= (self._drain_deadline or 0):
            for g in self._groups.values():
                for i in g.live_slots():
                    self._complete(g.rows[i], CANCELLED,
                                   "drain timeout")
                    g.release(i)
                    worked = True
            for job in self._pending:
                for req in job["reqs"]:
                    self._complete(req, CANCELLED, "drain timeout")
                    worked = True
                self._release_job_lease(job)
            self._pending.clear()
            for req in self.admission.drop_expired(float("inf")):
                self._complete(req, CANCELLED, "drain timeout")
                worked = True
            self._groups.clear()
            return worked
        # 3. cancel expired resident rows at the boundary
        for g in self._groups.values():
            for i in g.live_slots():
                req = g.rows[i]
                if req.deadline <= now:
                    self._complete(req, TIMEOUT, "cancelled at boundary")
                    trace_event("serve.cancel", cat="serve",
                                request=req.id, at_step=int(g.t_row[i]))
                    g.release(i)
                    worked = True
        # 4. joins: pull queued work into free slots, bucket by bucket
        for bucket, lane in self.admission.queued_buckets():
            g = self._groups.get((bucket, lane))
            if g is None:
                g = self._groups[(bucket, lane)] = _Group(
                    bucket, self.cfg.max_batch)
            free = g.free_slots()
            if not free:
                continue
            reqs = self.admission.take(bucket, len(free), lane)
            if reqs:
                slots = free[:len(reqs)]
                if self._prefix is not None and lane == "primary":
                    # peel prefix-pool hits off the cohort: each resumes
                    # from its donor rows (only the novel suffix
                    # prefills); misses keep the normal cohort path
                    reqs, slots = self._join_prefix_hits(g, lane, reqs,
                                                         slots)
                if reqs:
                    if self._engines[lane].serve_prefill_chunks(bucket):
                        self._start_chunked_join(g, lane, reqs, slots)
                    else:
                        self._join(g, lane, reqs, slots)
                worked = True
        # 4b. advance every in-flight chunked prefill by ONE chunk — the
        # point of chunking: the long forward yields to phase 5 between
        # chunks instead of holding the tick for the whole prompt
        for job in list(self._pending):
            self._advance_prefill(job)
            worked = True
        # 5. advance each group one segment
        for (bucket, lane), g in list(self._groups.items()):
            if g.live_slots():
                self._advance(g, lane)
                worked = True
            elif (not g.reserved and not self.admission.pending()):
                # empty group with no queued work: drop the cache memory
                del self._groups[(bucket, lane)]
        return worked

    def _cohort(self, g: _Group, reqs: list) -> tuple:
        """Pack a join cohort: padded to a power of two (capped at
        capacity) so join batches reuse a handful of compiled shapes."""
        k = len(reqs)
        n = 1
        while n < k:
            n *= 2
        n = min(n, g.capacity)
        prompts = np.zeros((n, g.bucket), np.int32)
        true_len = np.ones(n, np.int32)
        live = np.zeros(n, bool)
        ids = np.zeros(n, np.int32)
        for j, req in enumerate(reqs):
            prompts[j, :req.true_len] = req.prompt
            true_len[j] = req.true_len
            live[j] = True
            ids[j] = req.id
        return prompts, true_len, live, ids

    def _join(self, g: _Group, lane: str, reqs: list, slots: list) -> None:
        """Prefill a join cohort and splice it into the resident batch."""
        eng = self._engines[lane]
        variables = self._variables[lane]
        prompts, true_len, live, ids = self._cohort(g, reqs)
        t0 = monotonic()
        with span_on_tracer(self._tracer, "serve.prefill", cat="serve",
                            bucket=g.bucket, cohort=len(ids),
                            joins=len(reqs), lane=lane):
            tok, done, caches = eng.serve_prefill(
                variables, prompts, true_len, live, self._row_keys(ids))
            tok_h = np.asarray(tok)
        self.estimator.observe_prefill(g.bucket, monotonic() - t0)
        self._splice(g, lane, reqs, slots, list(range(len(reqs))),
                     tok_h, caches, prompts)

    def _join_prefix_hits(self, g: _Group, lane: str, reqs: list,
                          slots: list) -> tuple:
        """Try each join candidate against the prefix pool.  Hits resume
        from their donor rows — inline, or as a pending chunked-resume
        job when chunked prefill covers the suffix — and misses return
        for the normal cohort path.  The donor lease holds until the
        hit's splice lands (lease pinning: an in-flight resume can never
        lose its slots to eviction)."""
        eng = self._engines[lane]
        miss_reqs, miss_slots = [], []
        for req, slot in zip(reqs, slots):
            # match only whole chunks STRICTLY inside the prompt, so the
            # resumed prefill always recomputes the last prompt
            # position's logits itself
            limit = ((req.true_len - 1) // self._prefix.chunk
                     ) * self._prefix.chunk
            hit = (self._prefix.acquire(req.prompt, limit)
                   if limit else None)
            if hit is None:
                miss_reqs.append(req)
                miss_slots.append(slot)
                continue
            matched = hit.n_tokens
            self._count("prefix_hits")
            inc_counter("serve.prefix_hit")
            self._record_prefix({
                "event": "hit", "request": req.id, "bucket": g.bucket,
                "lane": lane, "matched": matched,
                "suffix": int(req.true_len) - matched})
            if eng.serve_resume_chunks(g.bucket, matched):
                self._start_chunked_resume(g, lane, req, slot, hit)
            else:
                self._join_resume(g, lane, req, slot, hit)
        return miss_reqs, miss_slots

    def _join_resume(self, g: _Group, lane: str, req: Request, slot: int,
                     hit) -> None:
        """Resume one prefix hit inline: dequantize/grow the donor rows,
        prefill the whole novel suffix in one traced-offset chunk call,
        finish, and splice — the same (tok, done, caches) contract as a
        fresh cohort prefill, so greedy outputs stay byte-identical."""
        eng = self._engines[lane]
        variables = self._variables[lane]
        matched = hit.n_tokens
        prompts = np.zeros((1, g.bucket), np.int32)
        prompts[0, :req.true_len] = req.prompt
        true_len = np.asarray([req.true_len], np.int32)
        ids = np.asarray([req.id], np.int32)
        t0 = monotonic()
        try:
            with span_on_tracer(self._tracer, "serve.prefill_resume",
                                cat="serve", bucket=g.bucket, lane=lane,
                                matched=matched,
                                suffix=int(req.true_len) - matched):
                tok, done, caches = eng.serve_prefill_resume(
                    variables, prompts, true_len, matched,
                    _assemble_prefix_row(hit.rows), np.ones(1, bool),
                    self._row_keys(ids))
                tok_h = np.asarray(tok)
            self.estimator.observe_prefill(g.bucket, monotonic() - t0)
            self._splice(g, lane, [req], [slot], [0], tok_h, caches,
                         prompts)
        finally:
            self._prefix.release(hit)

    def _start_chunked_resume(self, g: _Group, lane: str, req: Request,
                              slot: int, hit) -> None:
        """Queue a chunked RESUME: like `_start_chunked_join`, but the
        state opens from the donor rows and the chunk index starts past
        the matched prefix — `_advance_prefill` then runs the suffix one
        chunk per tick through the ordinary prefill_chunk program.  The
        donor lease holds across ticks until the splice."""
        eng = self._engines[lane]
        matched = hit.n_tokens
        prompts = np.zeros((1, g.bucket), np.int32)
        prompts[0, :req.true_len] = req.prompt
        g.reserved.add(slot)
        state = eng.serve_resume_init(_assemble_prefix_row(hit.rows),
                                      g.bucket)
        self._pending.append(dict(
            group=g, lane=lane, reqs=[req], slots=[slot],
            prompts=prompts,
            true_len=np.asarray([req.true_len], np.int32),
            live=np.ones(1, bool),
            ids=np.asarray([req.id], np.int32), state=state,
            index=matched // eng.prefill_chunk,
            chunks=eng.serve_prefill_chunks(g.bucket), elapsed=0.0,
            hit=hit))

    def _release_job_lease(self, job: dict) -> None:
        hit = job.get("hit")
        if hit is not None and self._prefix is not None:
            self._prefix.release(hit)
            job["hit"] = None

    def _start_chunked_join(self, g: _Group, lane: str, reqs: list,
                            slots: list) -> None:
        """Queue a chunked join: slots are reserved (not yet resident)
        and `_advance_prefill` runs ONE prompt chunk per tick until the
        cohort finishes and splices in."""
        prompts, true_len, live, ids = self._cohort(g, reqs)
        g.reserved.update(slots)
        eng = self._engines[lane]
        self._pending.append(dict(
            group=g, lane=lane, reqs=reqs, slots=slots, prompts=prompts,
            true_len=true_len, live=live, ids=ids, state=None, index=0,
            chunks=eng.serve_prefill_chunks(g.bucket), elapsed=0.0))

    def _advance_prefill(self, job: dict) -> None:
        """One chunk of an in-flight chunked prefill; on the last chunk,
        finish (sample + quantize) and splice the cohort in.  The
        estimator's prefill EWMA sees the SUMMED chunk time — feasibility
        math reflects the full prompt cost, not one slice of it."""
        g: _Group = job["group"]
        lane = job["lane"]
        eng = self._engines[lane]
        variables = self._variables[lane]
        t0 = monotonic()
        with span_on_tracer(self._tracer, "serve.prefill_chunk",
                            cat="serve", bucket=g.bucket, lane=lane,
                            index=job["index"], chunks=job["chunks"]):
            job["state"] = eng.serve_prefill_chunk(
                variables, job["prompts"], job["true_len"], job["index"],
                job["state"])
        job["elapsed"] += monotonic() - t0
        if self._run is not None:
            rec = {"event": "prefill_chunk", "bucket": g.bucket,
                   "lane": lane, "index": job["index"],
                   "chunks": job["chunks"],
                   "requests": [r.id for r in job["reqs"]]}
            traces = [r.trace.trace_id for r in job["reqs"]
                      if getattr(r, "trace", None) is not None]
            if traces:
                rec["traces"] = traces
            self._record_serve(rec)
        job["index"] += 1
        if job["index"] < job["chunks"]:
            return
        self._pending.remove(job)
        g.reserved.difference_update(job["slots"])
        t0 = monotonic()
        tok, done, caches = eng.serve_prefill_finish(
            job["state"], job["live"], self._row_keys(job["ids"]))
        tok_h = np.asarray(tok)
        job["elapsed"] += monotonic() - t0
        self.estimator.observe_prefill(g.bucket, job["elapsed"])
        # requests whose deadline passed while their prompt was still
        # chunking: finish as timeouts, splice only the survivors
        now = self.now()
        reqs, slots, src = [], [], []
        for j, (req, slot) in enumerate(zip(job["reqs"], job["slots"])):
            if req.finished:
                continue
            if req.deadline <= now:
                self._complete(req, TIMEOUT, "expired during prefill")
                continue
            reqs.append(req)
            slots.append(slot)
            src.append(j)
        if reqs:
            self._splice(g, lane, reqs, slots, src, tok_h, caches,
                         job["prompts"])
        self._release_job_lease(job)

    def _splice(self, g: _Group, lane: str, reqs: list, slots: list,
                src: list, tok_h, caches, prompts) -> None:
        """Merge cohort cache rows (and, on speculative lanes, the
        cohort's draft cache rows) into the group and seat the requests.

        On a PREFILL-tier engine this is where the work leaves: the
        finished cohort's caches go to the handoff bus instead of a
        resident slot, and each engine request ends `handoff` — the
        router's fleet request stays open until a decode replica splices
        the shipped rows and finishes the decode attempt."""
        eng = self._engines[lane]
        if self.role == "prefill" and self.handoff_export is not None:
            now = self.now()
            self.handoff_export(bucket=g.bucket, lane=lane, reqs=reqs,
                                src=src, tok_h=tok_h, caches=caches)
            for req in reqs:
                self._count("handoffs")
                trace_event("serve.handoff_out", cat="serve",
                            request=req.id, bucket=g.bucket, lane=lane,
                            **self._trace_fields(req))
                req.finish(HANDOFF, now)
            return
        if g.caches is None:
            g.caches = self._empty_caches(eng.module, g.capacity,
                                          g.bucket,
                                          kind=eng.cache_dtype)
        g.caches = DecodeEngine.merge_cache_rows(
            g.caches, caches, slots, src, mesh=eng.mesh)
        if eng.spec_tokens:
            dc = eng.serve_draft_prefill(self._draft_vars, prompts)
            if g.draft_caches is None:
                g.draft_caches = self._empty_caches(
                    eng.draft_module, g.capacity, g.bucket)
            g.draft_caches = DecodeEngine.merge_cache_rows(
                g.draft_caches, dc, slots, src, mesh=eng.mesh)
        for j, (req, slot) in zip(src, zip(reqs, slots)):
            g.rows[slot] = req
            g.tok[slot] = tok_h[j]
            g.true_len[slot] = req.true_len
            g.budget[slot] = req.max_new_tokens
            g.t_row[slot] = 0
            g.row_ids[slot] = req.id
            g.done[slot] = False
            trace_event("serve.join", cat="serve", request=req.id,
                        bucket=g.bucket, slot=slot, lane=lane,
                        **self._trace_fields(req))
            self._record_serve({"event": "join", "request": req.id,
                                "bucket": g.bucket, "slot": slot,
                                "lane": lane, **self._trace_fields(req)})
            if self._run is not None:
                # attempt-level TTFT: arrival at THIS engine to its first
                # emitted token (the fleet-level TTFT, arrival at the
                # router to the decode-tier splice, lands in handoff.py)
                self._run.observe_hist("serve.ttft_s",
                                       self.now() - req.arrival)
            self._emit(g, slot, [int(tok_h[j])])
        if self._prefix is not None and lane == "primary":
            self._insert_prefix_rows(reqs, src, caches)
            self._gauge_prefix()

    def _insert_prefix_rows(self, reqs: list, src: list, caches) -> None:
        """Pool each freshly spliced request's prompt-prefix slots: the
        greatest chunk multiple STRICTLY inside the prompt, so a later
        resume always recomputes the final prompt position itself.
        First-writer-wins per chunk; a refused eviction (every candidate
        leased) skips the deeper chunks rather than forcing anything."""
        chunk = self._prefix.chunk
        for j, req in zip(src, reqs):
            n = ((req.true_len - 1) // chunk) * chunk
            if n < chunk:
                continue
            row = [tuple(t[j:j + 1] for t in layer) for layer in caches]
            res = self._prefix.insert(req.prompt, n, row)
            if res["inserted"]:
                self._count("prefix_inserts", res["inserted"])
                self._record_prefix({
                    "event": "insert", "request": req.id,
                    "chunks": res["inserted"], "tokens": n})
            if res["evicted"]:
                self._count("prefix_evictions", res["evicted"])
                self._record_prefix({
                    "event": "evict", "chunks": res["evicted"],
                    "request": req.id})
            if res["refused"]:
                self._count("prefix_evictions_refused")
                inc_counter("serve.prefix_eviction_refused")
                self._record_prefix({"event": "evict_refused",
                                     "request": req.id})

    def _empty_caches(self, module, capacity: int, bucket: int,
                      kind: str = "model") -> list:
        import jax.numpy as jnp
        dh = module.d_model // module.n_heads
        w0 = _round_up(bucket + 1, self.cfg.cache_chunk)
        shape = (capacity, w0, module.n_heads, dh)
        if kind == "int8":
            # the quantized layout: int8 payloads + f32 per-(row, slot,
            # head) scales, matching _quantize_cache's 4-tuple
            sshape = (capacity, w0, module.n_heads)
            return [(jnp.zeros(shape, jnp.int8),
                     jnp.zeros(sshape, jnp.float32),
                     jnp.zeros(shape, jnp.int8),
                     jnp.zeros(sshape, jnp.float32))
                    for _ in range(module.n_layers)]
        return [(jnp.zeros(shape, module.dtype),
                 jnp.zeros(shape, module.dtype))
                for _ in range(module.n_layers)]

    def splice_remote(self, prompt: np.ndarray, max_new_tokens: int,
                      deadline: float, first_tok: int, src_caches,
                      lane: str = "primary", trace=None) -> Optional[Request]:
        """Seat one handed-off row (decode tier): merge the deserialized
        1-row cache into this engine's resident batch via the jitted
        `merge_cache_rows` and decode it to completion like any join.
        `trace` is the TraceContext that rode the kv_begin header — the
        decode attempt keeps the fleet request's trace id.  Returns the
        seated engine Request, or None when no slot is free or the
        engine is not alive — the handoff bus retries next tick (bounded
        by the transfer timeout and the request deadline)."""
        if not self.alive:
            return None
        eng = self._engines[lane]
        arr = np.asarray(prompt, np.int32)
        bucket = eng.bucket_for(arr.size)
        g = self._groups.get((bucket, lane))
        if g is None:
            g = self._groups[(bucket, lane)] = _Group(
                bucket, self.cfg.max_batch)
        free = g.free_slots()
        if not free:
            return None
        slot = free[0]
        now = self.now()
        req = Request(self._new_id(), arr, bucket, max_new_tokens, now,
                      float(deadline))
        req.trace = trace
        if g.caches is None:
            g.caches = self._empty_caches(eng.module, g.capacity, bucket,
                                          kind=eng.cache_dtype)
        g.caches = DecodeEngine.merge_cache_rows(
            g.caches, src_caches, [slot], [0], mesh=eng.mesh)
        g.rows[slot] = req
        g.tok[slot] = int(first_tok)
        g.true_len[slot] = req.true_len
        g.budget[slot] = req.max_new_tokens
        g.t_row[slot] = 0
        g.row_ids[slot] = req.id
        g.done[slot] = False
        self._count("remote_joins")
        trace_event("serve.handoff_in", cat="serve", request=req.id,
                    bucket=bucket, slot=slot, lane=lane,
                    **self._trace_fields(req))
        self._record_serve({"event": "remote_join", "request": req.id,
                            "bucket": bucket, "slot": slot, "lane": lane,
                            **self._trace_fields(req)})
        self._emit(g, slot, [int(first_tok)])
        if self._prefix is not None and lane == "primary":
            # the pool lives on the DECODE tier of a disaggregated
            # fleet: handed-off rows are the tier's only prefill source,
            # so they are what populates it (the prefill tier never
            # double-caches — ServeConfig rejects prefix_cache there)
            self._insert_prefix_rows([req], [0], src_caches)
            self._gauge_prefix()
        return req

    def _emit(self, g: _Group, slot: int, tokens: list) -> None:
        """Append emitted tokens to a row's request, honoring its budget
        and stop tokens; completes (and frees) the row when finished."""
        req = g.rows[slot]
        stopped = False
        appended = False
        for tok in tokens:
            if len(req.tokens) >= req.max_new_tokens:
                break
            req.tokens.append(int(tok))
            appended = True
            if self._stops.size and int(tok) in self._stops:
                stopped = True
                break
        if stopped or len(req.tokens) >= req.max_new_tokens:
            self._complete(req, OK)
            g.release(slot)
        elif appended:
            # segment-boundary flush point: wake any streaming reader
            # (finish() notifies on its own for the completed case)
            req.note_tokens()

    def _advance(self, g: _Group, lane: str) -> None:
        """Run one mixed-age segment (or, on speculative lanes, one
        draft-verify round) for a group and harvest the results."""
        if self._engines[lane].spec_tokens:
            self._advance_spec(g, lane)
            return
        eng = self._engines[lane]
        variables = self._variables[lane]
        seg = self.cfg.segment_steps
        live = g.live_slots()
        max_t = int(g.t_row[live].max()) if live else 0
        window = eng.serve_window(g.bucket, max_t, seg)
        t0 = monotonic()
        with span_on_tracer(self._tracer, "serve.segment", cat="serve",
                            bucket=g.bucket, lane=lane, seg_len=seg,
                            window=window, occupancy=round(
                                len(live) / g.capacity, 3)):
            caches, toks, tok, done = eng.serve_step(
                variables, g.caches, np.asarray(g.tok),
                np.asarray(g.done), g.true_len, g.budget, g.bucket,
                g.t_row, self._group_keys(g), seg, window)
            toks_h = np.asarray(toks)
            tok_h = np.asarray(tok)
            done_h = np.asarray(done)
        elapsed = monotonic() - t0
        self.estimator.observe_step(g.bucket, elapsed / seg)
        self._record_serve({"event": "segment", "bucket": g.bucket,
                            "lane": lane, "rows": len(live)})
        if self._run is not None:
            # per-token pacing: one sample per segment (segment wall over
            # its decode steps), not per token — bounded-cost by design
            self._run.observe_hist("serve.inter_token_s", elapsed / seg)
        g.caches = caches
        g.tok = tok_h.astype(np.int32)
        g.done = done_h.astype(bool)
        for i in live:
            if g.rows[i] is None:
                continue
            self._emit(g, i, toks_h[i].tolist())
            if g.rows[i] is not None:
                g.t_row[i] += seg
        if self._run is not None:
            self._run.gauge("serve.queue_depth", self.admission.pending())
            self._run.gauge("serve.in_flight", self.in_flight())
            self._gauge_prefix()

    def _advance_spec(self, g: _Group, lane: str) -> None:
        """One speculative round: the draft proposes, one target forward
        verifies, each row advances by its accepted count (+1).  The
        estimator's per-step EWMA sees round time divided by tokens
        actually emitted per live row — feasibility math tracks the
        measured speculative speedup, not the optimistic bound."""
        eng = self._engines[lane]
        variables = self._variables[lane]
        k1 = eng.spec_tokens + 1
        live = g.live_slots()
        max_t = int(g.t_row[live].max()) if live else 0
        window = eng.serve_window(g.bucket, max_t, k1)
        t0 = monotonic()
        with span_on_tracer(self._tracer, "serve.spec_round", cat="serve",
                            bucket=g.bucket, lane=lane, window=window,
                            occupancy=round(len(live) / g.capacity, 3)):
            (caches, draft_caches, toks, counts, tok, done,
             accepted) = eng.serve_spec_round(
                variables, self._draft_vars, g.caches, g.draft_caches,
                np.asarray(g.tok), np.asarray(g.done), g.true_len,
                g.budget, g.bucket, g.t_row, g.spec_rounds,
                self._group_keys(g), window)
            toks_h = np.asarray(toks)
            counts_h = np.asarray(counts)
            tok_h = np.asarray(tok)
            done_h = np.asarray(done)
            accepted_h = np.asarray(accepted)
        elapsed = monotonic() - t0
        g.spec_rounds += 1
        emitted = int(counts_h[live].sum())
        per_row = emitted / max(1, len(live))
        self.estimator.observe_step(g.bucket, elapsed / max(1.0, per_row))
        inc_counter("serve.spec_drafted_tokens",
                    eng.spec_tokens * len(live))
        inc_counter("serve.spec_accepted_tokens",
                    int(accepted_h[live].sum()))
        self._record_serve({"event": "segment", "bucket": g.bucket,
                            "lane": lane, "rows": len(live),
                            "spec": True, "emitted": emitted})
        if self._run is not None:
            self._run.observe_hist("serve.inter_token_s",
                                   elapsed / max(1.0, per_row))
        g.caches = caches
        g.draft_caches = draft_caches
        g.tok = tok_h.astype(np.int32)
        g.done = done_h.astype(bool)
        for i in live:
            if g.rows[i] is None:
                continue
            take = int(counts_h[i])
            if take:
                self._emit(g, i, toks_h[i][:take].tolist())
            if g.rows[i] is not None:
                g.t_row[i] += take
        if self._run is not None:
            self._run.gauge(
                "serve.spec_acceptance_rate",
                round(float(accepted_h[live].sum())
                      / max(1, eng.spec_tokens * len(live)), 4))
            self._run.gauge("serve.queue_depth", self.admission.pending())
            self._run.gauge("serve.in_flight", self.in_flight())

    # -- the loop (spawned by serve/lifecycle.py) -------------------------
    def _drained(self) -> bool:
        return (self._state == DRAINING and self.in_flight() == 0
                and self.admission.pending() == 0)

    def _loop(self) -> None:
        """The scheduler thread body: tick, check the SIGTERM guard,
        idle on the condition when there is no work."""
        while True:
            if (self._guard is not None and self._guard.triggered
                    and self._state == READY):
                self.begin_drain("sigterm")
            if self._state == STOPPED:
                return
            worked = self._tick()
            if self._drained():
                self._finish_drain()
                return
            if not worked:
                with self._wake:
                    self._wake.wait(timeout=0.01)

    # -- stats -------------------------------------------------------------
    def _percentile(self, q: float) -> Optional[float]:
        if not self._latencies:
            return None
        return float(np.percentile(np.asarray(self._latencies), q))

    def stats(self) -> dict:
        """Counts + latency percentiles (seconds) + breaker state — the
        dict the drills, bench arm, and gauges read."""
        out = dict(self._counts)
        out["in_flight"] = self.in_flight()
        out["queued"] = self.admission.pending()
        out["state"] = self._state
        out["breaker_state"] = self.breaker.state
        if self._prefix is not None:
            out["prefix"] = self._prefix.stats()
        for name, q in (("p50", 50), ("p95", 95), ("p99", 99)):
            p = self._percentile(q)
            out[f"latency_{name}_s"] = round(p, 6) if p is not None else None
        return out

    def prefix_stats(self) -> Optional[dict]:
        """The prefix pool's live stats dict (None when the pool is off)
        — surfaced per replica in `Replica.health()` and `/statz`."""
        return self._prefix.stats() if self._prefix is not None else None

    def _gauge_stats(self) -> None:
        if self._run is None:
            return
        for name, q in (("p50", 50), ("p95", 95), ("p99", 99)):
            p = self._percentile(q)
            if p is not None:
                self._run.gauge(f"serve.latency_{name}_ms", p * 1e3)
        for key in ("admitted", "shed", "ok", "timeout", "cancelled",
                    "degraded", "goodput_tokens"):
            self._run.gauge(f"serve.{key}", self._counts.get(key, 0))
