"""Resilient online serving over the decode engine.

The offline entry points (`transform(table)`) assume the whole workload
is in hand; serving inverts every premise — requests arrive when they
arrive, carry deadlines, and overload is the steady state, not the
exception.  This package is the robustness-first serving runtime the
ROADMAP's "millions of users" north star needs:

  * `admission` — bounded queue + deadline-feasibility admission control
    and the deadline-miss-rate breaker (shed at the front door, not by
    timing out in the back);
  * `engine` — the continuous-batching scheduler over `DecodeEngine`'s
    serve hooks (join at segment boundaries, cancel expired rows,
    degraded-mode failover to a quantized bundle);
  * `lifecycle` — warmup/readiness, the loop + HTTP threads (the ONE
    module allowed to spawn them — scripts/lint.py), SIGTERM -> graceful
    drain;
  * `http` — stdlib-only request front end + health endpoints
    (`/healthz`, `/readyz`, POST `/generate`), next to
    `observe/export.serve_metrics`.

docs/serving.md has the request lifecycle, policies, and knobs.
"""

from mmlspark_tpu.serve.admission import (AdmissionController,
                                          InvalidRequest, MissRateBreaker,
                                          Overloaded, StepTimeEstimator)
from mmlspark_tpu.serve.engine import ServeConfig, ServingEngine
from mmlspark_tpu.serve.lifecycle import serve_forever, start_engine, start_http
from mmlspark_tpu.serve.request import Request

__all__ = [
    "AdmissionController", "InvalidRequest", "MissRateBreaker",
    "Overloaded", "Request", "ServeConfig", "ServingEngine",
    "StepTimeEstimator", "serve_forever", "start_engine", "start_http",
]
