"""Resilient online serving over the decode engine.

The offline entry points (`transform(table)`) assume the whole workload
is in hand; serving inverts every premise — requests arrive when they
arrive, carry deadlines, and overload is the steady state, not the
exception.  This package is the robustness-first serving runtime the
ROADMAP's "millions of users" north star needs:

  * `admission` — bounded queue + deadline-feasibility admission control
    and the deadline-miss-rate breaker (shed at the front door, not by
    timing out in the back);
  * `engine` — the continuous-batching scheduler over `DecodeEngine`'s
    serve hooks (join at segment boundaries, cancel expired rows,
    degraded-mode failover to a quantized bundle);
  * `lifecycle` — warmup/readiness, the loop + HTTP threads (the ONE
    module allowed to spawn them — scripts/lint.py), SIGTERM -> graceful
    drain;
  * `replica` / `router` — the replicated fleet: N engine replicas
    behind a health-aware routing front tier (power-of-two-choices,
    outlier ejection with half-open probe re-admission, failover under
    a retry budget, optional hedging);
  * `handoff` — the disaggregated-tier KV handoff bus: prefill
    replicas ship finished (int8-capable) cache rows as crc-checked
    chunk pages over transport frames to the decode tier, with
    acks, watchdogs, and re-prefill failover (docs/serving.md
    "Disaggregated tiers");
  * `prefix_cache` — the cross-request radix prefix KV cache: chunk-
    granular trie of finished cache rows with LRU eviction and lease
    pinning, so requests sharing a prompt prefix prefill only their
    novel suffix (docs/serving.md "Prefix reuse & priority lanes");
  * `http` — stdlib-only request front end + health endpoints
    (`/healthz`, `/readyz`, POST `/generate` with optional chunked
    token streaming), next to `observe/export.serve_metrics`.

docs/serving.md has the request lifecycle, policies, and knobs.
"""

from mmlspark_tpu.serve.admission import (AdmissionController,
                                          InvalidRequest, MissRateBreaker,
                                          Overloaded, StepTimeEstimator)
from mmlspark_tpu.serve.engine import ServeConfig, ServingEngine
from mmlspark_tpu.serve.handoff import HandoffBus
from mmlspark_tpu.serve.lifecycle import (serve_forever, start_engine,
                                          start_http, start_router)
from mmlspark_tpu.serve.prefix_cache import PrefixCache, PrefixHit
from mmlspark_tpu.serve.replica import Replica, ReplicaUnavailable
from mmlspark_tpu.serve.request import Request
from mmlspark_tpu.serve.router import (RetryBudget, Router, RouterConfig,
                                       RouterRequest, build_fleet)

__all__ = [
    "AdmissionController", "HandoffBus", "InvalidRequest",
    "MissRateBreaker",
    "Overloaded", "PrefixCache", "PrefixHit", "Replica",
    "ReplicaUnavailable", "Request",
    "RetryBudget", "Router", "RouterConfig", "RouterRequest",
    "ServeConfig", "ServingEngine", "StepTimeEstimator", "build_fleet",
    "serve_forever", "start_engine", "start_http", "start_router",
]
