"""One replica of the serving fleet: a ServingEngine plus its health.

The router (serve/router.py) holds N in-process `Replica` handles and
makes every routing decision from what a replica's handle can PROVE
about it:

  * an EJECTION BREAKER — the PR-1 `CircuitBreaker` keyed
    `serve.replica.<name>`, tripped by consecutive failed attempts (or
    explicitly by the router on a miss-rate/hang breach).  OPEN means
    ejected: no new traffic until the cooldown elapses, then exactly one
    PROBE request is routed through the half-open gate; an on-time probe
    re-admits the replica.  The breaker is put in the process registry
    (`register_breaker`) so /metrics exports per-replica state for free.
  * a DEADLINE-MISS EWMA — `observe_completion(missed)` folds attempt
    outcomes into `miss_ewma`; the router ejects when it crosses the
    configured rate with enough samples.
  * a PROGRESS CLOCK — `tick()` refreshes `last_progress` whenever the
    engine did work or is idle; a replica that is BUSY but not
    progressing is hung, and the router ejects it on
    `now - last_progress > hang_timeout_s`.

Fault injection for the chaos drills acts on the handle, not the engine
internals: `inject_crash()` fails everything in flight (the work fails
over), `inject_hang()` freezes ticks with work resident, slow-degrade
throttles ticks by an integer factor, and `recover()` clears all of it.
A real exception escaping `engine._tick()` takes the same crash path —
the drill faults exercise exactly the machinery real faults use.

All timing reads the replica's injected resilience clock, so fleet tests
run on a `VirtualClock` with zero sleeps.
"""

from __future__ import annotations

from typing import Optional

from mmlspark_tpu.observe.logging import get_logger
from mmlspark_tpu.resilience.breaker import (CLOSED, OPEN, CircuitBreaker,
                                             register_breaker)
from mmlspark_tpu.resilience.clock import Clock, get_clock
from mmlspark_tpu.serve.admission import StepTimeEstimator
from mmlspark_tpu.serve.engine import ServingEngine
from mmlspark_tpu.serve.request import ERROR, Request


class ReplicaUnavailable(RuntimeError):
    """Submission refused by the replica handle itself (crashed or hung
    before the engine could even queue the request)."""


class _TeeEstimator(StepTimeEstimator):
    """Forwards a replica engine's prefill/segment measurements into the
    ROUTER's fleet-wide estimator as well as the replica's own — the
    router's admission feasibility math must reflect real decode speed
    without the router ever running a segment itself."""

    def __init__(self, sink: StepTimeEstimator, alpha: float = 0.3):
        super().__init__(alpha)
        self._sink = sink

    def observe_prefill(self, bucket: int, seconds: float) -> None:
        super().observe_prefill(bucket, seconds)
        self._sink.observe_prefill(bucket, seconds)

    def observe_step(self, bucket: int, seconds_per_step: float) -> None:
        super().observe_step(bucket, seconds_per_step)
        self._sink.observe_step(bucket, seconds_per_step)


class Replica:
    """One fleet member: engine + ejection breaker + health signals
    (module docstring).  Constructed around an un-warmed or warmed
    `ServingEngine`; the router warms all replicas in `warmup()`."""

    def __init__(self, name: str, engine: ServingEngine, *,
                 clock: Optional[Clock] = None, eject_failures: int = 3,
                 probe_reset_s: float = 5.0, miss_alpha: float = 0.2):
        self.name = name
        self.engine = engine
        self.role = engine.cfg.role
        self._clock = clock
        # the ejection gate: consecutive attempt failures open it; the
        # half-open probe is a real routed request.  Disaggregated tiers
        # get their own breaker keying (`serve.prefill.p0`,
        # `serve.decode.d0`) so per-tier ejection state is separable in
        # /metrics; colocated replicas keep the PR-10 key.
        key = (f"serve.{self.role}.{name}" if self.role != "colocated"
               else f"serve.replica.{name}")
        self.breaker = register_breaker(CircuitBreaker(
            key, threshold=max(1, int(eject_failures)),
            reset_s=float(probe_reset_s), clock=clock))
        self.draining = False          # per-replica SIGTERM drain flag
        self.miss_alpha = float(miss_alpha)
        self.miss_ewma = 0.0
        self.miss_samples = 0
        self.routed = 0                 # attempts dispatched here
        self.completed_ok = 0
        self.last_progress = self.now()
        self.probe: Optional[Request] = None   # in-flight half-open probe
        self._crashed = False
        self._hung = False
        self._slow_every = 1            # tick throttle (1 = full speed)
        self._slow_phase = 0
        self._crash_detail = ""

    def now(self) -> float:
        return (self._clock or get_clock()).monotonic()

    @property
    def faulted(self) -> bool:
        """The handle KNOWS the replica is dead (crashed or hung) — an
        unambiguous fault the router ejects on immediately instead of
        waiting out the consecutive-failure threshold."""
        return self._crashed or self._hung

    @property
    def crashed(self) -> bool:
        """Crash is OBSERVABLE at the handle (the process exited), unlike
        a hang (which only the router's progress clock can call) — the
        router force-ejects a crashed replica as soon as it sees the
        flag, even if no request was in flight to fail."""
        return self._crashed

    def adopt_estimator(self, sink: StepTimeEstimator) -> None:
        """Rewire the engine's measurements to tee into the router's
        fleet estimator (called once, at router construction)."""
        tee = _TeeEstimator(sink, alpha=sink.alpha)
        self.engine.estimator = tee
        self.engine.admission.estimator = tee

    # -- health signals ----------------------------------------------------
    def busy(self) -> bool:
        return (self.engine.in_flight() + self.engine.admission.pending()) > 0

    def load_tokens(self) -> int:
        """Tokens still owed by this replica (resident + queued) — the
        load signal power-of-two-choices compares."""
        return (self.engine.in_flight_tokens()
                + self.engine.admission.queued_tokens())

    def routable(self) -> bool:
        """May receive NORMAL traffic: engine ready, handle healthy, and
        the ejection breaker closed.  A slow replica stays routable —
        ejection needs evidence (misses), not suspicion."""
        return (not self._crashed and not self._hung and not self.draining
                and self.engine.ready and self.breaker.state == CLOSED)

    def probe_due(self) -> bool:
        """Ejected, cooled down, and no probe in flight: the next
        dispatch should route ONE request here through the half-open
        gate.  A still-dead replica fails its probe and restarts the
        cooldown — the probe IS the health check."""
        return (self.breaker.state == OPEN and self.breaker.retry_in_s() <= 0
                and self.probe is None and not self.draining)

    def begin_drain(self, reason: str = "sigterm") -> None:
        """Per-replica SIGTERM: stop taking new traffic and drain by the
        TIER's semantics.  A prefill replica finishes its queued and
        in-flight prefills — and, via the router, its in-flight KV
        transfers — before stopping; a decode replica finishes or
        cancels its resident rows under the engine's drain budget.  The
        router's drain-finalize phase stops the engine once it (and, for
        prefill, the handoff bus) is empty."""
        if self.draining or not self.engine.alive:
            return
        self.draining = True
        self.engine.begin_drain(f"replica_drain:{reason}")

    def observe_completion(self, missed: bool) -> float:
        """Fold one attempt outcome into the deadline-miss EWMA; returns
        the updated rate (the router's miss-rate ejection reads it)."""
        self.miss_ewma += self.miss_alpha * (float(missed) - self.miss_ewma)
        self.miss_samples += 1
        return self.miss_ewma

    def reset_miss_ewma(self) -> None:
        """Clear the miss evidence (on probe re-admission: the replica
        earns a fresh record, exactly like MissRateBreaker's window
        clear)."""
        self.miss_ewma = 0.0
        self.miss_samples = 0

    # -- submission / scheduling ------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               deadline_s: Optional[float] = None,
               priority: Optional[str] = None, trace=None) -> Request:
        """Route one attempt to this replica's engine; raises
        `ReplicaUnavailable` when the handle knows the engine is dead
        (crashed/hung) — the router records it as a dispatch failure.
        `trace` is the router's per-attempt TraceContext child: the
        engine attempt inherits the fleet request's trace id."""
        if self._crashed:
            raise ReplicaUnavailable(
                f"replica {self.name} crashed: {self._crash_detail}")
        if self._hung:
            raise ReplicaUnavailable(f"replica {self.name} is hung")
        return self.engine.submit(prompt, max_new_tokens,
                                  deadline_s=deadline_s, priority=priority,
                                  trace=trace)

    def tick(self) -> bool:
        """Advance the engine one scheduler pass, honoring injected
        faults; refreshes `last_progress` (work done, or idle — only a
        busy-but-stuck replica looks hung).  A real exception escaping
        the engine takes the crash path: its in-flight work fails and
        the router fails it over."""
        if self._crashed or self._hung:
            return False
        self._slow_phase += 1
        if self._slow_every > 1 and self._slow_phase % self._slow_every:
            return False
        try:
            worked = self.engine._tick()
        except Exception as e:
            self.crash(f"engine tick raised: {e!r}")
            return False
        if worked or not self.busy():
            self.last_progress = self.now()
        return worked

    def fail_inflight(self, detail: str) -> int:
        """Fail every resident and queued request on this replica as
        `error` (their router requests fail over); returns how many were
        failed.  Used by `crash()` and by the router's hang ejection."""
        now = self.now()
        failed = 0
        for g in list(self.engine._groups.values()):
            for i in g.live_slots():
                g.rows[i].finish(ERROR, now, detail)
                g.release(i)
                failed += 1
        # mid-chunked-prefill cohorts hold only RESERVED slots — their
        # requests live in the pending-job list, not in any row
        for job in list(self.engine._pending):
            self.engine._release_job_lease(job)
            for req in job["reqs"]:
                if not req.finished:
                    req.finish(ERROR, now, detail)
                    failed += 1
        self.engine._pending.clear()
        self.engine._groups.clear()
        for req in self.engine.admission.drop_expired(float("inf")):
            req.finish(ERROR, now, detail)
            failed += 1
        return failed

    # -- fault injection (chaos drills + real-fault path) ------------------
    def crash(self, detail: str = "replica crashed") -> int:
        """Kill the replica: everything in flight fails immediately (the
        router retries it elsewhere).  The engine object survives for
        `recover()` — a crashed process's replacement comes up warm from
        the persistent compilation cache, which this models."""
        self._crashed = True
        self._crash_detail = detail
        failed = self.fail_inflight(detail)
        get_logger("serve").warning(
            "replica %s crashed (%s): %d in-flight attempts failed over",
            self.name, detail, failed)
        return failed

    inject_crash = crash

    def inject_hang(self) -> None:
        """Freeze the replica with its work resident: ticks do nothing,
        requests never finish, `last_progress` stops moving — the hang
        detector's job."""
        self._hung = True

    def inject_slow(self, factor: float = 4.0) -> None:
        """Degrade throughput: the engine only advances every `factor`-th
        tick.  The replica stays routable; only miss evidence ejects it."""
        self._slow_every = max(1, int(factor))
        self._slow_phase = 0

    def recover(self) -> None:
        """Clear all injected faults (the flap scenario's 'process came
        back') and restart the progress clock.  The ejection breaker is
        NOT touched: re-admission must go through the half-open probe."""
        if self._hung:
            # a hang clears with its wedged work still resident; fail it
            # so the router's requests are not stranded
            self.fail_inflight(f"replica {self.name} restarted after hang")
        self._crashed = False
        self._hung = False
        self._slow_every = 1
        self._crash_detail = ""
        self.last_progress = self.now()

    # -- introspection -----------------------------------------------------
    def in_flight_rows(self) -> list:
        """Per-row view of resident work (the /statz replica section)."""
        now = self.now()
        rows = []
        for g in list(self.engine._groups.values()):
            for i in g.live_slots():
                req = g.rows[i]
                if req is None:
                    continue
                rows.append({"request": req.id, "bucket": g.bucket,
                             "tokens": len(req.tokens),
                             "deadline_in_s": round(req.deadline - now, 3)})
        return rows

    def health(self) -> dict:
        """Point-in-time health for /statz, gauges, and the drills."""
        prefix = self.engine.prefix_stats()
        return {"state": self.engine.state,
                **({"prefix": prefix} if prefix is not None else {}),
                "ready": self.engine.ready,
                "role": self.role,
                "draining": self.draining,
                "routable": self.routable(),
                "breaker": self.breaker.snapshot(),
                "miss_ewma": round(self.miss_ewma, 4),
                "miss_samples": self.miss_samples,
                "in_flight": self.engine.in_flight(),
                "queued": self.engine.admission.pending(),
                "load_tokens": self.load_tokens(),
                "in_flight_rows": self.in_flight_rows(),
                "routed": self.routed,
                "completed_ok": self.completed_ok,
                "crashed": self._crashed,
                "hung": self._hung,
                "slow_factor": self._slow_every}
