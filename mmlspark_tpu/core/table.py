"""DataTable: host-side columnar table with ML metadata.

The TPU-native replacement for Spark DataFrames.  The reference distributes
rows across Spark partitions and runs per-row JVM/JNI UDF loops
(ImageTransformer.scala:272-304, CNTKModel.scala:50-104); here a table is a
dict of contiguous numpy columns living on the host, whose numeric/image
columns materialize as (sharded) `jax.Array`s only at the device boundary —
so every per-row loop in the reference becomes one batched XLA program.

Partitioning survives as `num_shards`, a layout hint consumed by the parallel
layer (repartition == resharding over the mesh, reference Repartition.scala).
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterator, Mapping, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.schema import ColumnMeta, _json_scalar


def object_column(values: Any) -> np.ndarray:
    """Build a 1-D object column without numpy coercing nested sequences."""
    values = list(values)
    out = np.empty(len(values), dtype=object)
    out[:] = values
    return out


def _as_column(values: Any) -> np.ndarray:
    if isinstance(values, np.ndarray):
        return values
    values = list(values)
    if values and isinstance(values[0], (str, bytes, dict)) or any(
            v is None for v in values):
        arr = np.empty(len(values), dtype=object)
        arr[:] = values
        return arr
    try:
        return np.asarray(values)
    except ValueError:  # ragged
        arr = np.empty(len(values), dtype=object)
        arr[:] = values
        return arr


class DataTable:
    """Immutable-by-convention columnar table.

    Mutating helpers (`set_meta`) mutate metadata only; all data-shaping
    methods return new DataTables sharing column buffers (zero-copy where
    possible).
    """

    def __init__(
        self,
        columns: Mapping[str, Any],
        metadata: Optional[Mapping[str, ColumnMeta]] = None,
        num_shards: int = 1,
    ):
        self._cols: dict[str, np.ndarray] = {}
        n = None
        for name, vals in columns.items():
            arr = _as_column(vals)
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise ValueError(
                    f"column '{name}' has {len(arr)} rows, expected {n}")
            self._cols[name] = arr
        self._meta: dict[str, ColumnMeta] = {
            name: (metadata[name].copy() if metadata and name in metadata
                   else ColumnMeta())
            for name in self._cols
        }
        self.num_shards = max(1, int(num_shards))

    # -- construction --------------------------------------------------
    @staticmethod
    def from_dict(d: Mapping[str, Any], **kw) -> "DataTable":
        return DataTable(d, **kw)

    @staticmethod
    def from_pandas(df, **kw) -> "DataTable":
        cols = {}
        for name in df.columns:
            s = df[name]
            if s.dtype == object:
                cols[name] = s.to_numpy(dtype=object)
            else:
                cols[name] = s.to_numpy()
        return DataTable(cols, **kw)

    @staticmethod
    def from_rows(rows: Sequence[Mapping[str, Any]], **kw) -> "DataTable":
        if not rows:
            return DataTable({}, **kw)
        names = list(rows[0].keys())
        return DataTable({n: [r[n] for r in rows] for n in names}, **kw)

    @staticmethod
    def read_csv(path: str, **kw) -> "DataTable":
        import pandas as pd
        return DataTable.from_pandas(pd.read_csv(path), **kw)

    def to_pandas(self):
        import pandas as pd
        out = {}
        for name, arr in self._cols.items():
            out[name] = list(arr) if arr.ndim > 1 else arr
        return pd.DataFrame(out)

    # -- basic accessors -----------------------------------------------
    @property
    def columns(self) -> list[str]:
        return list(self._cols)

    @property
    def num_rows(self) -> int:
        if not self._cols:
            return 0
        return len(next(iter(self._cols.values())))

    def __len__(self) -> int:
        return self.num_rows

    def __contains__(self, name: str) -> bool:
        return name in self._cols

    def __getitem__(self, name: str) -> np.ndarray:
        try:
            return self._cols[name]
        except KeyError:
            raise KeyError(
                f"no column '{name}'; available: {self.columns}") from None

    def column(self, name: str) -> np.ndarray:
        return self[name]

    def meta(self, name: str) -> ColumnMeta:
        self[name]
        return self._meta[name]

    def set_meta(self, name: str, meta: ColumnMeta) -> None:
        self[name]
        self._meta[name] = meta

    def schema(self) -> dict[str, tuple]:
        return {n: (str(a.dtype), a.shape[1:]) for n, a in self._cols.items()}

    def rows(self) -> Iterator[dict]:
        for i in range(self.num_rows):
            yield {n: a[i] for n, a in self._cols.items()}

    # -- shaping (all return new tables) --------------------------------
    def _derive(self, cols: dict[str, np.ndarray],
                meta: Optional[dict[str, ColumnMeta]] = None) -> "DataTable":
        t = DataTable.__new__(DataTable)
        t._cols = cols
        src_meta = meta if meta is not None else self._meta
        t._meta = {n: (src_meta[n].copy() if n in src_meta else ColumnMeta())
                   for n in cols}
        t.num_shards = self.num_shards
        return t

    def select(self, *names: str) -> "DataTable":
        return self._derive({n: self[n] for n in names})

    def drop(self, *names: str) -> "DataTable":
        return self._derive({n: a for n, a in self._cols.items() if n not in names})

    def with_column(self, name: str, values: Any,
                    meta: Optional[ColumnMeta] = None) -> "DataTable":
        arr = _as_column(values)
        if self._cols and len(arr) != self.num_rows:
            raise ValueError(
                f"column '{name}' has {len(arr)} rows, table has {self.num_rows}")
        cols = dict(self._cols)
        cols[name] = arr
        out = self._derive(cols)
        if meta is not None:
            out._meta[name] = meta.copy()
        elif name not in self._meta:
            out._meta[name] = ColumnMeta()
        return out

    def rename(self, mapping: Mapping[str, str]) -> "DataTable":
        cols = {mapping.get(n, n): a for n, a in self._cols.items()}
        meta = {mapping.get(n, n): m for n, m in self._meta.items()}
        return self._derive(cols, meta)

    def filter(self, mask: Any) -> "DataTable":
        mask = np.asarray(mask)
        return self._derive({n: a[mask] for n, a in self._cols.items()})

    def take(self, n: int) -> "DataTable":
        return self._derive({name: a[:n] for name, a in self._cols.items()})

    def slice(self, start: int, stop: int) -> "DataTable":
        return self._derive({n: a[start:stop] for n, a in self._cols.items()})

    def sample(self, fraction: float, seed: int = 0) -> "DataTable":
        rng = np.random.default_rng(seed)
        mask = rng.random(self.num_rows) < fraction
        return self.filter(mask)

    def shuffle(self, seed: int = 0) -> "DataTable":
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self.num_rows)
        return self._derive({n: a[perm] for n, a in self._cols.items()})

    def concat(self, other: "DataTable") -> "DataTable":
        if set(self.columns) != set(other.columns):
            raise ValueError(
                f"column mismatch: {self.columns} vs {other.columns}")
        cols = {n: np.concatenate([self[n], other[n]], axis=0)
                for n in self.columns}
        return self._derive(cols)

    def repartition(self, num_shards: int) -> "DataTable":
        """Resharding hint (reference Repartition.scala:15-42)."""
        out = self._derive(dict(self._cols))
        out.num_shards = max(1, int(num_shards))
        return out

    def drop_nulls(self, subset: Optional[Sequence[str]] = None) -> "DataTable":
        names = list(subset) if subset else self.columns
        mask = np.ones(self.num_rows, dtype=bool)
        for n in names:
            a = self[n]
            if a.dtype == object:
                mask &= np.asarray([v is not None for v in a])
            elif np.issubdtype(a.dtype, np.floating):
                ax = tuple(range(1, a.ndim))
                mask &= ~np.isnan(a).any(axis=ax) if a.ndim > 1 else ~np.isnan(a)
        return self.filter(mask)

    def find_unused_column_name(self, prefix: str) -> str:
        """Reference: DatasetExtensions.findUnusedColumnName, DatasetExtensions.scala:58."""
        name, i = prefix, 0
        while name in self._cols:
            i += 1
            name = f"{prefix}_{i}"
        return name

    # -- batching (the applyModel minibatcher, CNTKModel.scala:50-104) ---
    def batches(self, columns: Sequence[str], batch_size: int,
                pad: bool = True) -> Iterator[tuple[dict[str, np.ndarray], int]]:
        """Yield (column-dict, valid_count) minibatches.

        The last batch is zero-padded to `batch_size` when `pad` — static
        shapes keep XLA from recompiling per remainder (the reference padded
        for a CNTK batch-size bug, CNTKModel.scala:71-76; here padding is a
        compilation-model requirement, not a workaround).
        """
        n = self.num_rows
        for start in range(0, n, batch_size):
            stop = min(start + batch_size, n)
            valid = stop - start
            batch = {c: self[c][start:stop] for c in columns}
            if pad and valid < batch_size:
                batch = {
                    c: np.concatenate(
                        [a, np.zeros((batch_size - valid,) + a.shape[1:], a.dtype)])
                    for c, a in batch.items()
                }
            yield batch, valid

    # -- persistence ----------------------------------------------------
    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        obj_cols, arr_cols = {}, {}
        for n, a in self._cols.items():
            (obj_cols if a.dtype == object else arr_cols)[n] = a
        np.savez(os.path.join(path, "columns.npz"), **arr_cols)
        with open(os.path.join(path, "objects.json"), "w") as f:
            json.dump({n: [_obj_to_json(v) for v in a]
                       for n, a in obj_cols.items()}, f)
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump({
                "num_shards": self.num_shards,
                "column_order": self.columns,
                "metadata": {n: m.to_json() for n, m in self._meta.items()},
            }, f)

    @staticmethod
    def load(path: str) -> "DataTable":
        with open(os.path.join(path, "meta.json")) as f:
            info = json.load(f)
        npz = np.load(os.path.join(path, "columns.npz"), allow_pickle=False)
        with open(os.path.join(path, "objects.json")) as f:
            objs = json.load(f)
        cols: dict[str, np.ndarray] = {}
        for n in info["column_order"]:
            cols[n] = npz[n] if n in npz.files else _as_column(
                [_obj_from_json(v) for v in objs[n]])
        meta = {n: ColumnMeta.from_json(m) for n, m in info["metadata"].items()}
        return DataTable(cols, metadata=meta, num_shards=info["num_shards"])

    def __repr__(self):
        schema = ", ".join(f"{n}:{d}{list(s) if s else ''}"
                           for n, (d, s) in self.schema().items())
        return f"DataTable[{self.num_rows} rows; {schema}]"


def _obj_to_json(v):
    if isinstance(v, bytes):
        import base64
        return {"__bytes__": base64.b64encode(v).decode()}
    if isinstance(v, np.ndarray):
        return {"__array__": v.tolist(), "dtype": str(v.dtype)}
    return _json_scalar(v)


def _obj_from_json(v):
    if isinstance(v, dict) and "__bytes__" in v:
        import base64
        return base64.b64decode(v["__bytes__"])
    if isinstance(v, dict) and "__array__" in v:
        return np.asarray(v["__array__"], dtype=v["dtype"])
    return v
