"""Parameter DSL for pipeline stages.

TPU-native counterpart of the reference's MMLParams/Wrappable param system
(reference: src/core/contracts/src/main/scala/Params.scala:10-134): every
stage declares typed `Param`s with defaults, optional value domains and
validators; params are introspectable (driving the fuzzing harness and the
thin auto-generated API docs) and JSON-serializable (driving save/load).

Unlike the JVM design there is no codegen step — the core is already Python —
but the same contracts hold: params are discoverable by reflection, have
stable names, and round-trip through persistence.
"""

from __future__ import annotations

import copy as _copy
from typing import Any, Callable, Optional, Sequence

_SENTINEL = object()


class ParamError(ValueError):
    """Raised on invalid parameter values (reference Exceptions.scala:21-35)."""


class Param:
    """A typed, named parameter attached to a Params subclass.

    Acts as a descriptor: reading from an instance returns the instance's
    value (or the default); writing validates and stores.
    """

    def __init__(
        self,
        default: Any = _SENTINEL,
        doc: str = "",
        *,
        ptype: Optional[type] = None,
        domain: Optional[Sequence[Any]] = None,
        validator: Optional[Callable[[Any], bool]] = None,
        required: bool = False,
    ):
        self.name: str = ""  # filled in by __set_name__
        self.doc = doc
        self.ptype = ptype
        self.domain = tuple(domain) if domain is not None else None
        self.validator = validator
        self.required = required
        self.has_default = default is not _SENTINEL
        self.default = default if self.has_default else None

    def __set_name__(self, owner, name):
        self.name = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return obj.get(self.name)

    def __set__(self, obj, value):
        obj.set(self.name, value)

    def validate(self, value: Any) -> Any:
        if value is None:
            return value
        if self.ptype is not None:
            if self.ptype is float and isinstance(value, int) and not isinstance(value, bool):
                value = float(value)
            if not isinstance(value, self.ptype):
                expected = (self.ptype.__name__ if isinstance(self.ptype, type)
                            else "/".join(t.__name__ for t in self.ptype))
                raise ParamError(
                    f"param '{self.name}' expects {expected}, "
                    f"got {type(value).__name__}: {value!r}")
        if self.domain is not None and value not in self.domain:
            raise ParamError(
                f"param '{self.name}' value {value!r} not in domain {self.domain}")
        if self.validator is not None and not self.validator(value):
            raise ParamError(f"param '{self.name}' value {value!r} failed validation")
        return value

    def __repr__(self):
        return f"Param(name={self.name!r}, default={self.default!r})"


class Params:
    """Base class providing the param protocol.

    Subclasses declare class-level `Param` attributes. Instance values are
    kept in `_paramMap`; defaults live on the Param objects themselves, so
    `explain_params` / persistence can distinguish set-vs-default (the same
    distinction SparkML's ParamMap keeps).
    """

    def __init__(self, **kwargs):
        self._paramMap: dict[str, Any] = {}
        self.set_params(**kwargs)

    # -- introspection -------------------------------------------------
    @classmethod
    def params(cls) -> dict[str, Param]:
        """All declared params, including inherited ones (MRO order)."""
        cached = cls.__dict__.get("_params_cache")
        if cached is not None:
            return cached
        out: dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for k, v in vars(klass).items():
                if isinstance(v, Param):
                    out[k] = v
        cls._params_cache = out
        return out

    @classmethod
    def has_param(cls, name: str) -> bool:
        return name in cls.params()

    def explain_params(self) -> str:
        lines = []
        for name, p in sorted(self.params().items()):
            cur = self._paramMap.get(name, _SENTINEL)
            state = f"current: {cur!r}" if cur is not _SENTINEL else (
                f"default: {p.default!r}" if p.has_default else "unset")
            lines.append(f"{name}: {p.doc} ({state})")
        return "\n".join(lines)

    # -- get/set -------------------------------------------------------
    def get(self, name: str) -> Any:
        p = self._param(name)
        if name in self._paramMap:
            return self._paramMap[name]
        if p.has_default:
            return p.default
        return None

    def is_set(self, name: str) -> bool:
        self._param(name)
        return name in self._paramMap

    def set(self, name: str, value: Any) -> "Params":
        p = self._param(name)
        self._paramMap[name] = p.validate(value)
        return self

    def set_params(self, **kwargs) -> "Params":
        for k, v in kwargs.items():
            self.set(k, v)
        return self

    def clear(self, name: str) -> "Params":
        self._paramMap.pop(name, None)
        return self

    def _param(self, name: str) -> Param:
        try:
            return self.params()[name]
        except KeyError:
            raise ParamError(
                f"{type(self).__name__} has no param '{name}'; "
                f"available: {sorted(self.params())}") from None

    def _check_required(self):
        for name, p in self.params().items():
            if p.required and name not in self._paramMap:
                raise ParamError(
                    f"{type(self).__name__}: required param '{name}' is not set")

    # -- copy ----------------------------------------------------------
    def copy(self, **overrides) -> "Params":
        new = _copy.copy(self)
        new._paramMap = dict(self._paramMap)
        new.set_params(**overrides)
        return new

    # -- persistence helpers (JSON-safe values only) -------------------
    def param_values(self, set_only: bool = True) -> dict[str, Any]:
        if set_only:
            return dict(self._paramMap)
        return {name: self.get(name) for name in self.params()}


# ---------------------------------------------------------------------------
# Shared column traits (reference Params.scala:112-134 HasInputCol et al.)
# ---------------------------------------------------------------------------

class HasInputCol(Params):
    inputCol = Param(None, "name of the input column", ptype=str)


class HasOutputCol(Params):
    outputCol = Param(None, "name of the output column", ptype=str)


class HasInputCols(Params):
    inputCols = Param(None, "names of the input columns", ptype=(list, tuple))


class HasLabelCol(Params):
    labelCol = Param("label", "name of the label column", ptype=str)


class HasFeaturesCol(Params):
    featuresCol = Param("features", "name of the features column", ptype=str)


def domain(*values) -> tuple:
    """Helper mirroring the reference's string-domain params (Params.scala:103-108)."""
    return tuple(values)
