"""Column-metadata protocol: score kinds, categorical levels, image schema.

TPU-native counterpart of the reference's metadata-driven schema system
(reference: src/core/schema/src/main/scala/SparkSchema.scala:183-245,
SchemaConstants.scala:9-43, Categoricals.scala:17-261, ImageSchema.scala:18-23,
BinaryFileSchema.scala:14-17).

The reference smuggles ML semantics through Spark column `Metadata` under an
`mml` tag: which columns are scores, which model produced them, what the
categorical levels are.  Here the same protocol lives in `ColumnMeta` objects
carried by `DataTable` (core/table.py) — evaluators like
ComputeModelStatistics discover the scored-label/score columns by metadata,
never by hard-coded names, exactly as the reference does
(ComputeModelStatistics.scala:205-218).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Optional

import numpy as np


# --------------------------------------------------------------------------
# SchemaConstants (reference SchemaConstants.scala:9-43)
# --------------------------------------------------------------------------

class SchemaConstants:
    MML_TAG = "mml"                     # metadata namespace tag
    SCORE_MODEL_PREFIX = "score_model"  # value identifying the producing model
    SCORE_COLUMN_KIND = "score_column_kind"

    # score column kinds
    SCORES_COLUMN = "scores"
    SCORED_LABELS_COLUMN = "scored_labels"
    SCORED_PROBABILITIES_COLUMN = "scored_probabilities"
    TRUE_LABELS_COLUMN = "true_labels"

    # model categories
    CLASSIFICATION_KIND = "classification"
    REGRESSION_KIND = "regression"

    SPARK_PREDICTION_COLUMN = "prediction"


@dataclasses.dataclass
class CategoricalMap:
    """Bidirectional value<->index map for a categorical column.

    Reference: CategoricalMap, Categoricals.scala:186-261.  `levels[i]` is the
    raw value encoded as index i; `has_null_level` marks a reserved index for
    missing values (the reference's MML-style null level).
    """

    levels: list
    ordinal: bool = False
    has_null_level: bool = False

    def __post_init__(self):
        self._index: dict = {v: i for i, v in enumerate(self.levels)}

    @property
    def num_levels(self) -> int:
        return len(self.levels)

    def get_index(self, value, default: int = -1) -> int:
        return self._index.get(value, default)

    def get_level(self, index: int):
        return self.levels[index]

    def to_indices(self, values) -> np.ndarray:
        return np.asarray([self._index.get(v, -1) for v in values], dtype=np.int32)

    def to_levels(self, indices) -> np.ndarray:
        idx = np.asarray(indices, dtype=np.int64)
        if ((idx < 0) | (idx >= len(self.levels))).any():
            out = np.empty(len(idx), dtype=object)
            for i, j in enumerate(idx):
                out[i] = self.levels[j] if 0 <= j < len(self.levels) else None
            return out
        arr = np.asarray(self.levels, dtype=object)
        return arr[idx]

    # persistence ----------------------------------------------------------
    def to_json(self) -> dict:
        return {
            "levels": [_json_scalar(v) for v in self.levels],
            "ordinal": self.ordinal,
            "has_null_level": self.has_null_level,
        }

    @staticmethod
    def from_json(d: dict) -> "CategoricalMap":
        return CategoricalMap(list(d["levels"]), bool(d.get("ordinal", False)),
                              bool(d.get("has_null_level", False)))


def _json_scalar(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (np.bool_,)):
        return bool(v)
    if isinstance(v, np.str_):
        return str(v)
    return v


# --------------------------------------------------------------------------
# Image / binary-file schemas (reference ImageSchema.scala:18-23,
# BinaryFileSchema.scala:14-17)
# --------------------------------------------------------------------------

@dataclasses.dataclass
class ImageSchema:
    """Shape/layout contract for an image column.

    An image column in a DataTable is a numpy uint8 array of shape
    (rows, height, width, channels) — batched HWC, the layout host decoders
    produce — plus this metadata.  The reference kept per-row
    (path, height, width, type, bytes) structs; batching is the TPU-native
    re-design: images live as one dense tensor ready for device transfer.
    """

    height: int
    width: int
    channels: int = 3
    color_space: str = "BGR"  # reference uses OpenCV BGR byte order

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(d: dict) -> "ImageSchema":
        return ImageSchema(**d)


@dataclasses.dataclass
class BinaryFileSchema:
    """Marks a column of raw file bytes (list of `bytes`), with paths alongside."""

    path_col: Optional[str] = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(d: dict) -> "BinaryFileSchema":
        return BinaryFileSchema(**d)


# --------------------------------------------------------------------------
# ColumnMeta — the per-column metadata record
# --------------------------------------------------------------------------

@dataclasses.dataclass
class ColumnMeta:
    """Everything the `mml` metadata tag carried in the reference.

    score_model / score_kind / model_kind implement the scored-columns
    protocol (SparkSchema.scala:183-245); categorical carries levels
    (Categoricals.scala); image/binary mark tensorized payload columns.
    """

    score_model: Optional[str] = None      # uid of producing model
    score_kind: Optional[str] = None       # one of SchemaConstants.*_COLUMN
    model_kind: Optional[str] = None       # classification | regression
    categorical: Optional[CategoricalMap] = None
    image: Optional[ImageSchema] = None
    binary: Optional[BinaryFileSchema] = None
    extra: dict = dataclasses.field(default_factory=dict)

    def copy(self) -> "ColumnMeta":
        return ColumnMeta(
            score_model=self.score_model,
            score_kind=self.score_kind,
            model_kind=self.model_kind,
            categorical=self.categorical,
            image=self.image,
            binary=self.binary,
            extra=dict(self.extra),
        )

    @property
    def is_categorical(self) -> bool:
        return self.categorical is not None

    def to_json(self) -> dict:
        d: dict[str, Any] = {}
        if self.score_model is not None:
            d["score_model"] = self.score_model
        if self.score_kind is not None:
            d["score_kind"] = self.score_kind
        if self.model_kind is not None:
            d["model_kind"] = self.model_kind
        if self.categorical is not None:
            d["categorical"] = self.categorical.to_json()
        if self.image is not None:
            d["image"] = self.image.to_json()
        if self.binary is not None:
            d["binary"] = self.binary.to_json()
        if self.extra:
            d["extra"] = self.extra
        return d

    @staticmethod
    def from_json(d: dict) -> "ColumnMeta":
        return ColumnMeta(
            score_model=d.get("score_model"),
            score_kind=d.get("score_kind"),
            model_kind=d.get("model_kind"),
            categorical=CategoricalMap.from_json(d["categorical"]) if "categorical" in d else None,
            image=ImageSchema.from_json(d["image"]) if "image" in d else None,
            binary=BinaryFileSchema.from_json(d["binary"]) if "binary" in d else None,
            extra=d.get("extra", {}),
        )


# --------------------------------------------------------------------------
# Schema helpers (reference SparkSchema.scala object methods)
# --------------------------------------------------------------------------

_score_tag_seq = itertools.count(1)


def set_score_column(table, model_uid: str, column: str, score_kind: str,
                     model_kind: str) -> None:
    """Tag `column` as a score column produced by `model_uid` (in place).

    Reference: SparkSchema.setColumnName/updateMetadata, SparkSchema.scala:183-236.
    """
    meta = table.meta(column)
    meta.score_model = model_uid
    meta.score_kind = score_kind
    meta.model_kind = model_kind
    meta.extra["score_seq"] = next(_score_tag_seq)
    table.set_meta(column, meta)


def find_score_columns(table, model_uid: Optional[str] = None) -> dict[str, str]:
    """Map score_kind -> column name for columns tagged by `model_uid`.

    If model_uid is None, uses the most recently tagged model (the reference
    evaluator picks the scores of "the" model in the DataFrame the same way,
    ComputeModelStatistics.scala:205-218, 523-530).  Recency is tracked by a
    tagging sequence number, not column order.
    """
    tagged = {c: m for c in table.columns
              if (m := table.meta(c)).score_model is not None}
    if model_uid is None:
        if not tagged:
            return {}
        latest = max(tagged.values(), key=lambda m: m.extra.get("score_seq", 0))
        model_uid = latest.score_model
    return {m.score_kind: c for c, m in tagged.items() if m.score_model == model_uid}


def make_categorical(table, column: str, levels: Optional[list] = None,
                     ordinal: bool = False, output_col: Optional[str] = None):
    """Encode a column to categorical indices with levels in metadata.

    Reference: SparkSchema.makeCategorical, SparkSchema.scala:255-307.
    Returns a new table where `output_col` (default: in place) holds int32
    indices and carries a CategoricalMap.
    """
    values = table[column]
    vals_list = list(values.tolist() if isinstance(values, np.ndarray) else values)
    if levels is None:
        seen: dict = {}
        for v in vals_list:
            if v not in seen:
                seen[v] = len(seen)
        if ordinal:
            levels = list(seen)
        elif all(isinstance(v, (int, float)) and not isinstance(v, bool)
                 for v in seen):
            levels = sorted(seen)  # numeric order, NOT string order
        else:
            levels = sorted(seen, key=lambda v: (str(type(v)), str(v)))
    cmap = CategoricalMap(list(levels), ordinal=ordinal)
    indices = cmap.to_indices(vals_list)
    out = output_col or column
    new = table.with_column(out, indices)
    meta = new.meta(out)
    meta.categorical = cmap
    new.set_meta(out, meta)
    return new


def get_categorical_map(table, column: str) -> Optional[CategoricalMap]:
    return table.meta(column).categorical
