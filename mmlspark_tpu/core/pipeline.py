"""Pipeline kernel: PipelineStage / Transformer / Estimator / Pipeline.

TPU-native counterpart of the SparkML pipeline contracts the reference builds
everything on: stateless `Transformer.transform(table)`, `Estimator.fit(table)
-> Transformer`, composable `Pipeline`, and save/load for every stage from day
one (the reference's fuzzing harness, src/fuzzing/Fuzzing.scala:35-104, treats
persistence + fit/transform as the universal invariants — we keep that).

Persistence layout per stage directory:
    stage.json   {"class": "pkg.mod.Class", "uid": ..., "params": {...}}
    extra/       stage-specific payload (arrays, nested stages) via
                 _save_extra/_load_extra hooks — the analogue of the
                 reference's composite MLWriters (AssembleFeatures.scala:410-497).
"""

from __future__ import annotations

import importlib
import itertools
import json
import os
from typing import Optional, Sequence

import numpy as np

from mmlspark_tpu.core.params import Param, Params
from mmlspark_tpu.core.table import DataTable

_uid_counters = itertools.count()

# Per-row error policy for row-wise transforms (the reference's graceful-
# degradation convention: one bad row must be able to NOT abort a batch):
#   "fail"    raise on the first bad row (the default — silent data loss
#             is never opt-out);
#   "skip"    drop bad rows from the output;
#   "column"  keep every row; bad rows get a placeholder value and the
#             error message lands in an `<output>_error` object column
#             (None for healthy rows) so downstream stages can route or
#             audit failures.
ON_ERROR_POLICIES = ("fail", "skip", "column")


def check_on_error(policy: str) -> str:
    """Validate an on_error policy value (shared by stages and readers)."""
    if policy not in ON_ERROR_POLICIES:
        raise ValueError(
            f"on_error must be one of {ON_ERROR_POLICIES}, got {policy!r}")
    return policy


def record_skipped_rows(stage: str, count: int,
                        reason: str = "on_error=skip") -> None:
    """Make `on_error='skip'` row drops VISIBLE at the run level.

    Graceful degradation that is silent is data loss with extra steps:
    a reader quietly shrinking batches looks identical to a smaller
    corpus.  Every skip site (the image readers, row-wise transforms)
    reports its drop count here — one `rows.skipped_on_error` process
    counter (lands in run_summary counter deltas) plus a cat=resilience
    trace event (lands in the run-report resilience timeline) and a
    warning, so a run that lost rows says so in every surface."""
    if count <= 0:
        return
    from mmlspark_tpu.observe.logging import get_logger
    from mmlspark_tpu.observe.metrics import inc_counter
    from mmlspark_tpu.observe.trace import trace_event
    inc_counter("rows.skipped_on_error", float(count))
    trace_event("rows.skipped", cat="resilience", stage=stage,
                rows=int(count), reason=reason)
    get_logger("core").warning("%s: skipped %d row(s) (%s)", stage,
                               count, reason)


def _fresh_uid(cls_name: str) -> str:
    return f"{cls_name}_{next(_uid_counters):04d}"


class PipelineStage(Params):
    """Base of all pipeline stages; adds uid + persistence to Params."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.uid = _fresh_uid(type(self).__name__)

    def __init_subclass__(cls, **kwargs):
        # Every stage's fit/transform is wrapped for the opt-in stage timer
        # (observe/timing.py) — one contextvar check when inactive.  Wrapping
        # happens at class creation so stages defined outside the framework
        # are covered too.
        super().__init_subclass__(**kwargs)
        from mmlspark_tpu.observe.timing import instrument_stage_method
        for method in ("fit", "transform"):
            fn = cls.__dict__.get(method)
            if fn is not None and not getattr(
                    fn, "__mmlspark_instrumented__", False):
                setattr(cls, method,
                        instrument_stage_method(method, fn))

    # -- persistence ----------------------------------------------------
    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        cls = type(self)
        payload = {
            "class": f"{cls.__module__}.{cls.__qualname__}",
            "uid": self.uid,
            "params": {k: _param_to_json(v)
                       for k, v in self.param_values(set_only=True).items()},
        }
        with open(os.path.join(path, "stage.json"), "w") as f:
            json.dump(payload, f, indent=1)
        extra = os.path.join(path, "extra")
        os.makedirs(extra, exist_ok=True)
        self._save_extra(extra)

    @classmethod
    def load(cls, path: str) -> "PipelineStage":
        stage = load_stage(path)
        if not isinstance(stage, cls):
            raise TypeError(f"{path} holds {type(stage).__name__}, not {cls.__name__}")
        return stage

    def _save_extra(self, path: str) -> None:  # override for array state
        pass

    def _load_extra(self, path: str) -> None:
        pass

    def __repr__(self):
        set_params = ", ".join(f"{k}={v!r}" for k, v in self._paramMap.items())
        return f"{type(self).__name__}({set_params})"


def load_stage(path: str) -> PipelineStage:
    """Load any saved stage, dispatching on the recorded class path."""
    with open(os.path.join(path, "stage.json")) as f:
        payload = json.load(f)
    module_name, _, qualname = payload["class"].rpartition(".")
    module = importlib.import_module(module_name)
    cls = module
    for part in qualname.split("."):
        cls = getattr(cls, part)
    # Prefer the subclass constructor so instance state set in __init__
    # exists on the loaded object; fall back to __new__ for stages whose
    # __init__ requires arguments (they must restore state in _load_extra).
    try:
        stage = cls()
    except TypeError:
        stage = cls.__new__(cls)
        PipelineStage.__init__(stage)
    stage._paramMap = {}
    stage.uid = payload["uid"]
    for k, v in payload["params"].items():
        stage.set(k, _param_from_json(v))
    stage._load_extra(os.path.join(path, "extra"))
    return stage


def _param_to_json(v):
    if isinstance(v, np.ndarray):
        return {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, tuple):
        return list(v)
    return v


def _param_from_json(v):
    if isinstance(v, dict) and "__ndarray__" in v:
        return np.asarray(v["__ndarray__"], dtype=v["dtype"])
    return v


class Transformer(PipelineStage):
    """A stateless table -> table mapping."""

    on_error = Param(
        "fail", "per-row error policy for row-wise transforms: 'fail' "
        "raises on the first bad row, 'skip' drops it, 'column' keeps the "
        "row and records the message in an '<output>_error' column",
        ptype=str, domain=ON_ERROR_POLICIES)

    def transform(self, table: DataTable) -> DataTable:
        raise NotImplementedError

    def __call__(self, table: DataTable) -> DataTable:
        return self.transform(table)


class Estimator(PipelineStage):
    """Fits on a table, producing a Transformer (the "Model")."""

    def fit(self, table: DataTable) -> Transformer:
        raise NotImplementedError


class Evaluator(Transformer):
    """A transformer that computes metric tables (ComputeModelStatistics style)."""


class Pipeline(Estimator):
    """Sequence of stages; fit() fits estimators in order, threading transforms.

    Mirrors SparkML Pipeline semantics the reference relies on
    (e.g. TrainClassifier.scala:158-159).
    """

    def __init__(self, stages: Optional[Sequence[PipelineStage]] = None, **kwargs):
        super().__init__(**kwargs)
        self._stages: list[PipelineStage] = list(stages or [])

    def get_stages(self) -> list[PipelineStage]:
        return list(self._stages)

    def set_stages(self, stages: Sequence[PipelineStage]) -> "Pipeline":
        self._stages = list(stages)
        return self

    def fit(self, table: DataTable) -> "PipelineModel":
        fitted: list[Transformer] = []
        current = table
        for i, stage in enumerate(self._stages):
            if isinstance(stage, Estimator):
                model = stage.fit(current)
            elif isinstance(stage, Transformer):
                model = stage
            else:
                raise TypeError(f"stage {i} ({stage!r}) is neither Estimator "
                                f"nor Transformer")
            if i < len(self._stages) - 1:
                current = model.transform(current)
            fitted.append(model)
        return PipelineModel(fitted)

    def _save_extra(self, path: str) -> None:
        _save_stage_list(path, self._stages)

    def _load_extra(self, path: str) -> None:
        self._stages = _load_stage_list(path)


class PipelineModel(Transformer):
    """The fitted pipeline: applies each stage's transform in order."""

    def __init__(self, stages: Optional[Sequence[Transformer]] = None, **kwargs):
        super().__init__(**kwargs)
        self._stages: list[Transformer] = list(stages or [])

    def get_stages(self) -> list[Transformer]:
        return list(self._stages)

    def transform(self, table: DataTable) -> DataTable:
        current = table
        for stage in self._stages:
            current = stage.transform(current)
        return current

    def _save_extra(self, path: str) -> None:
        _save_stage_list(path, self._stages)

    def _load_extra(self, path: str) -> None:
        self._stages = _load_stage_list(path)


def _save_stage_list(path: str, stages: Sequence[PipelineStage]) -> None:
    with open(os.path.join(path, "stages.json"), "w") as f:
        json.dump({"count": len(stages)}, f)
    for i, stage in enumerate(stages):
        stage.save(os.path.join(path, f"stage_{i:03d}"))


def _load_stage_list(path: str) -> list:
    with open(os.path.join(path, "stages.json")) as f:
        count = json.load(f)["count"]
    return [load_stage(os.path.join(path, f"stage_{i:03d}"))
            for i in range(count)]
