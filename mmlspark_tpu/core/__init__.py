from mmlspark_tpu.core.params import Param, Params
from mmlspark_tpu.core.pipeline import (
    Estimator,
    Pipeline,
    PipelineModel,
    PipelineStage,
    Transformer,
    load_stage,
)
from mmlspark_tpu.core.table import DataTable
