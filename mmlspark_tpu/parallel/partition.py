"""Partition-rule registry: regex over param-tree paths -> PartitionSpec.

The tensor-parallel layer of the mesh story (docs/parallelism.md): where
`parallel/mesh.py` names the axes and `parallel/bridge.py` moves bytes,
this module decides WHICH axis each weight lives on.  A rule set is an
ordered sequence of ``(regex, PartitionSpec)`` pairs matched against the
'/'-joined path of every param-tree leaf — first match wins, exactly the
fmengine/fmtrainer `match_partition_rules` contract:

    >>> match_partition_rules({"mlp_up": {"kernel": w}})  # DEFAULT_RULES
    {'mlp_up': {'kernel': PartitionSpec(None, 'model')}}

Invariants (test-pinned in tests/test_partition.py):

  * scalar / size-1 leaves are NEVER sharded, whatever the rules say —
    a PartitionSpec over a scalar is meaningless and GSPMD rejects it;
  * rank-1 ``bias`` leaves are never sharded (the per-shard bias add is
    already free under any activation layout);
  * int8 ``kernel_scale`` leaves (quant/quantize.py layout) follow their
    kernel's OUTPUT-channel spec — a column-parallel kernel's scales ride
    the same axis, a row-parallel kernel's scales replicate;
  * an unmatched leaf follows the explicit ``on_unmatched`` policy:
    ``"raise"`` (the default — silent replication of a tensor you meant
    to shard is how HBM blows up at scale) or ``"replicate"``.

This module is also the ONE place `with_sharding_constraint` /
`NamedSharding` construction is allowed to live (scripts/lint.py forbids
both outside `parallel/`, the same seam as the bridge/device_put rule):
model code states WHERE a value should live via `shard_constraint(x,
spec)` and the mesh in scope decides whether that means anything — on a
1-D (or absent) mesh the hint is a no-op, so forwards stay portable.
"""

from __future__ import annotations

import contextlib
import re
import threading
from typing import Any, Iterable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mmlspark_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS

# -- rule sets ---------------------------------------------------------------

# One rule: (regex searched over the '/'-joined tree path, spec).
Rule = tuple[str, P]

UNMATCHED_RAISE = "raise"
UNMATCHED_REPLICATE = "replicate"

# The TransformerLM layout (models/definitions.py param names), per the
# standard Megatron split: column-parallel producers (qkv, mlp_up, lm_head)
# shard their OUTPUT channels over "model" so each chip computes its own
# heads / hidden slice; row-parallel consumers (proj, mlp_down) shard their
# INPUT channels so the activation never re-gathers between the pair (one
# psum at the block boundary, inserted by GSPMD).  Expert stacks (E, D, H)
# shard the expert axis — expert parallelism through the same registry.
# Embeddings, norms, the MoE router, and everything unnamed replicate.
DEFAULT_RULES: tuple = (
    (r"(qkv|mlp_up|lm_head)/kernel$", P(None, MODEL_AXIS)),
    (r"(proj|mlp_down)/kernel$", P(MODEL_AXIS, None)),
    (r"moe/(w_in|w_out)$", P(MODEL_AXIS, None, None)),
    (r".*", P()),
)

# Activation/cache hints for the transformer forward (shard_constraint
# call sites in models/definitions.py and models/generate.py): attention
# tensors carry heads on "model" at axis 2 of (B, S, H, D); the MLP hidden
# carries its channel slice on "model"; the decode KV cache (B, W, H, D)
# keeps batch on "data" and heads on "model" so every segment/merge
# program preserves the layout.
HEADS_SPEC = P(DATA_AXIS, None, MODEL_AXIS, None)
HIDDEN_SPEC = P(DATA_AXIS, None, MODEL_AXIS)
KV_CACHE_SPEC = P(DATA_AXIS, None, MODEL_AXIS, None)
KV_SCALE_SPEC = P(DATA_AXIS, None, MODEL_AXIS)

# Speculative decoding (models/generate.py): the DRAFT model's cache rides
# the data axis only — a draft sized for low latency rarely has a head
# count the mesh's model axis divides, and its whole forward is a
# rounding error next to the target's, so replicating its heads costs
# nothing while keeping the verify program (which runs the TARGET layout
# above) free to shard.  Draft params replicate for the same reason.
DRAFT_KV_CACHE_SPEC = P(DATA_AXIS, None, None, None)
DRAFT_KV_SCALE_SPEC = P(DATA_AXIS, None, None)

# Sequence-sharded decode (models/generate.py seq path): the KV cache's
# WINDOW axis splits over "seq" — each chip owns a contiguous slab of
# cache slots, the decode step merges per-shard softmax statistics
# (ops/attention.merge_attention_stats) instead of gathering the window.
# Heads stay unsharded: the seq engine path refuses model>1 meshes, so
# naming MODEL_AXIS here would only demote on the meshes that reach it.
SEQ_KV_CACHE_SPEC = P(DATA_AXIS, SEQ_AXIS, None, None)
SEQ_KV_SCALE_SPEC = P(DATA_AXIS, SEQ_AXIS, None)


def path_str(path: Sequence) -> str:
    """'/'-joined form of a jax tree_map_with_path key path — the string
    the rule regexes are matched against."""
    parts = []
    for k in path:
        parts.append(str(getattr(k, "key", getattr(k, "idx", k))))
    return "/".join(parts)


_path_str = path_str  # internal alias (pre-public-name call sites)


def _axes_of(spec: P) -> set:
    axes = set()
    for entry in spec:
        if entry is None:
            continue
        for a in (entry if isinstance(entry, (tuple, list)) else (entry,)):
            axes.add(a)
    return axes


def _match(path: str, rules: Sequence[Rule], on_unmatched: str) -> P:
    for pattern, spec in rules:
        if re.search(pattern, path):
            return spec
    if on_unmatched == UNMATCHED_REPLICATE:
        return P()
    raise ValueError(
        f"no partition rule matched param path {path!r} "
        f"(on_unmatched='raise'; add a rule or a catch-all ('.*', P()))")


def leaf_spec(path: str, shape: Sequence[int], rules: Sequence[Rule],
              on_unmatched: str = UNMATCHED_RAISE) -> P:
    """The spec for ONE leaf: scalar/bias invariants first, then rules."""
    shape = tuple(int(s) for s in shape)
    if len(shape) == 0 or int(np.prod(shape)) == 1:
        return P()  # scalar leaves are always unsharded
    name = path.rsplit("/", 1)[-1]
    if name == "bias" and len(shape) == 1:
        return P()  # 1-D biases are never sharded
    if name.endswith("_scale"):
        # int8 kernel_scale (out,) follows its kernel's output-channel
        # axis: the last entry of the kernel's spec (quant/quantize.py
        # stores one scale per output channel, so a column-parallel
        # kernel's scales shard with it; row-parallel scales replicate)
        kernel_spec = _match(path[:-len("_scale")], rules, on_unmatched)
        last = kernel_spec[-1] if len(kernel_spec) else None
        return P(last) if last is not None else P()
    return _match(path, rules, on_unmatched)


def match_partition_rules(tree: Any, rules: Optional[Sequence[Rule]] = None,
                          *, on_unmatched: str = UNMATCHED_RAISE) -> Any:
    """A spec pytree (same structure as `tree`), first matching rule wins.

    `tree` leaves may be arrays or anything with a ``.shape`` (live jax
    Arrays, ShapeDtypeStructs, numpy) — only shapes are read.
    """
    if on_unmatched not in (UNMATCHED_RAISE, UNMATCHED_REPLICATE):
        raise ValueError(
            f"on_unmatched must be 'raise' or 'replicate', got "
            f"{on_unmatched!r}")
    rule_list = tuple(DEFAULT_RULES if rules is None else rules)
    for pattern, spec in rule_list:
        re.compile(pattern)  # surface a bad regex at the call site
        if not isinstance(spec, P):
            raise TypeError(f"rule for {pattern!r} must map to a "
                            f"PartitionSpec, got {type(spec).__name__}")

    def assign(path, leaf):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            shape = np.shape(leaf)
        return leaf_spec(_path_str(path), shape, rule_list, on_unmatched)

    return jax.tree_util.tree_map_with_path(assign, tree)


def compatible_spec(spec: P, shape: Sequence[int],
                    mesh: Optional[Mesh]) -> P:
    """Demote `spec` to P() when `shape` cannot actually be tiled by it.

    A spec longer than the leaf's rank, or naming a mesh axis whose size
    does not divide the corresponding dim (or that the mesh lacks), would
    be a GSPMD error — the rule registry describes the flagship layout,
    but scoring/restore must also accept trees the rules were not written
    for (conv models, odd vocab sizes).  Demotion to replicated is always
    correct, merely less parallel.
    """
    shape = tuple(shape)
    if len(spec) == 0:
        return spec
    if mesh is None or len(spec) > len(shape):
        return P()
    for dim, entry in zip(shape, spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        size = 1
        for a in axes:
            if a not in mesh.shape:
                return P()
            size *= mesh.shape[a]
        if size and dim % size:
            return P()
    return spec


# -- NamedSharding construction (the sanctioned site) ------------------------

def named_sharding(mesh: Mesh, spec: P = P()) -> NamedSharding:
    """Construct a NamedSharding — the one allowed construction site
    outside mesh.py (scripts/lint.py keeps raw construction in parallel/)."""
    return NamedSharding(mesh, spec)


def tree_shardings(mesh: Mesh, tree: Any,
                   rules: Optional[Sequence[Rule]] = None, *,
                   on_unmatched: str = UNMATCHED_RAISE) -> Any:
    """NamedSharding pytree for `tree` under `rules` — specs demoted per
    leaf shape (compatible_spec), so the result is always placeable."""
    specs = match_partition_rules(tree, rules, on_unmatched=on_unmatched)

    def build(leaf, spec):
        shape = getattr(leaf, "shape", None) or np.shape(leaf)
        return NamedSharding(mesh, compatible_spec(spec, shape, mesh))

    return jax.tree_util.tree_map(build, tree, specs)


def make_shard_fns(mesh: Mesh, specs: Any) -> Any:
    """Per-leaf placement callables from a spec pytree (the fmengine
    `make_shard_and_gather_fns` shard half): each fn device_puts its leaf
    onto the mesh under its (shape-validated) spec."""

    def one(spec):
        def put(x):
            s = compatible_spec(spec, np.shape(x), mesh)
            return jax.device_put(x, NamedSharding(mesh, s))
        return put

    return jax.tree_util.tree_map(one, specs,
                                  is_leaf=lambda s: isinstance(s, P))


def make_gather_fns(mesh: Mesh, specs: Any) -> Any:
    """Per-leaf gather callables: sharded leaf -> full host np.ndarray.

    The checkpoint/bundle-save direction — gathered arrays carry their
    full logical shape, so what lands on disk is topology-portable
    (restore re-commits onto whatever mesh is live via
    bridge.put_tree_like).  Under multi-host the identity jit with
    replicated out_shardings performs the all-gather; single-process
    arrays are fully addressable and fetch directly.
    """
    rep = NamedSharding(mesh, P())

    def one(_spec):
        def gather(x):
            if isinstance(x, jax.Array) and not x.is_fully_addressable:
                x = jax.jit(lambda t: t, out_shardings=rep)(x)
            return np.asarray(jax.device_get(x))
        return gather

    return jax.tree_util.tree_map(one, specs,
                                  is_leaf=lambda s: isinstance(s, P))


def shard_tree(tree: Any, mesh: Mesh,
               rules: Optional[Sequence[Rule]] = None, *,
               on_unmatched: str = UNMATCHED_RAISE) -> Any:
    """Place a host pytree onto the mesh per the rule set (convenience
    over match_partition_rules + make_shard_fns)."""
    specs = match_partition_rules(tree, rules, on_unmatched=on_unmatched)
    fns = make_shard_fns(mesh, specs)
    return jax.tree_util.tree_map(lambda f, x: f(x), fns, tree)


def gather_tree(tree: Any, mesh: Mesh) -> Any:
    """Gather a (possibly sharded) pytree to full host arrays."""
    specs = jax.tree_util.tree_map(lambda _: P(), tree)
    fns = make_gather_fns(mesh, specs)
    return jax.tree_util.tree_map(lambda f, x: f(x), fns, tree)


# -- rule-set serialization (ModelBundle metadata round-trip) ----------------

def rules_to_json(rules: Sequence[Rule]) -> list:
    """JSON-able form: [[pattern, [axis|null|[axis,...], ...]], ...]."""
    out = []
    for pattern, spec in rules:
        entries = []
        for entry in spec:
            if isinstance(entry, (tuple, list)):
                entries.append(list(entry))
            else:
                entries.append(entry)
        out.append([pattern, entries])
    return out


def rules_from_json(data: Iterable) -> tuple:
    """Inverse of rules_to_json; tolerates JSON's lists-for-tuples."""
    rules = []
    for pattern, entries in data:
        spec_entries = [tuple(e) if isinstance(e, list) else e
                        for e in entries]
        rules.append((str(pattern), P(*spec_entries)))
    return tuple(rules)


# -- activation sharding hints (the sanctioned constraint site) --------------

_local = threading.local()


@contextlib.contextmanager
def use_mesh(mesh: Optional[Mesh]):
    """Make `mesh` the target of shard_constraint hints traced inside.

    Wrapped around jit DISPATCH sites (Trainer step, TPUModel apply,
    DecodeEngine segments): tracing happens inside the first call, so the
    hints bake this mesh into that mesh's compiled program.  None is a
    no-op context (hints fall back to any ambient `with mesh:` scope).
    """
    if mesh is None:
        yield None
        return
    stack = getattr(_local, "mesh_stack", None)
    if stack is None:
        stack = _local.mesh_stack = []
    stack.append(mesh)
    try:
        yield mesh
    finally:
        stack.pop()


def active_mesh() -> Optional[Mesh]:
    """The mesh shard_constraint hints currently target: the innermost
    use_mesh scope, else jax's ambient `with mesh:` context, else None."""
    stack = getattr(_local, "mesh_stack", None)
    if stack:
        return stack[-1]
    try:
        from jax.interpreters import pxla
        env_mesh = pxla.thread_resources.env.physical_mesh
        if env_mesh is not None and not env_mesh.empty:
            return env_mesh
    except Exception:
        pass
    return None


def shard_constraint(x: Any, spec: P) -> Any:
    """`with_sharding_constraint` that degrades to identity off-mesh.

    The ONE sanctioned constraint call site (scripts/lint.py): forwards
    state where a value should live, and the mesh in scope decides what
    that means.  No active mesh, a mesh lacking the named axes, or a
    shape the spec cannot tile -> the value passes through untouched, so
    the same module code runs on a laptop CPU and a dp x mp slice.
    """
    mesh = active_mesh()
    if mesh is None:
        return x
    axes = _axes_of(spec)
    if not axes or not axes.issubset(set(mesh.axis_names)):
        return x
    s = compatible_spec(spec, np.shape(x), mesh)
    if len(s) == 0 and len(spec) != 0:
        return x  # demoted: the hint cannot tile this shape on this mesh
    try:
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, s))
    except Exception:
        return x  # a hint must never take down a forward it only advises


def expert_constraint(x: Any, axis: str) -> Any:
    """MoE dispatch hint: expert-major slabs live on the expert axis
    (ops/moe.py's slot tensor) — axis-name form of shard_constraint."""
    return shard_constraint(x, P(axis))
