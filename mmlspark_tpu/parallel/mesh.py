"""Device mesh construction and sharding specs.

The TPU-native replacement for the reference's process-level parallelism:
Spark partitions for scoring (CNTKModel.scala:215-221) and the `mpiexec` MPI
ring for training (CommandBuilders.scala:79-117) both collapse into one
abstraction — a `jax.sharding.Mesh` over the slice's chips, with XLA inserting
collectives over ICI (and DCN across slices).  Standard axis names:

    data   - data parallelism (batch axis)         [replaces Spark partitions / MPI ranks]
    model  - tensor/model parallelism               (new-design headroom)
    seq    - sequence/context parallelism           (new-design headroom)

The reference detected parallel width with `nvidia-smi -L`
(EnvironmentUtils.scala:20-50); here width is `jax.device_count()`.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mmlspark_tpu import config

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"

MESH_DATA = config.register(
    "MMLSPARK_TPU_MESH_DATA", default=-1, ptype=int,
    doc="Data-parallel mesh width for the default dp x mp mesh "
        "(mesh_spec_from_config); -1 = all devices left over after the "
        "model axis.")

MESH_MODEL = config.register(
    "MMLSPARK_TPU_MESH_MODEL", default=1, ptype=int,
    doc="Tensor/model-parallel mesh width for the default dp x mp mesh: "
        "weights matched by the partition rules (parallel/partition.py) "
        "shard over this many chips. 1 (default) keeps every path "
        "data-parallel-only.")

MESH_SEQ = config.register(
    "MMLSPARK_TPU_MESH_SEQ", default=1, ptype=int,
    doc="Sequence-parallel mesh width for the default mesh: long-context "
        "decode shards the KV-cache window over this many chips "
        "(blockwise ring prefill + cross-chip softmax-stats merge, "
        "models/generate.py). Composes with MESH_DATA; mutually "
        "exclusive with MESH_MODEL>1 on the decode path. 1 (default) "
        "keeps the single-chip window.")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape; -1 means "all remaining devices"."""

    data: int = -1
    model: int = 1
    seq: int = 1

    def resolve(self, n_devices: Optional[int] = None) -> dict[str, int]:
        n = n_devices if n_devices is not None else jax.device_count()
        sizes = {"data": self.data, "model": self.model, "seq": self.seq}
        fixed = int(np.prod([s for s in sizes.values() if s > 0]))
        free = [k for k, s in sizes.items() if s <= 0]
        if len(free) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {free}")
        if free:
            if n % fixed:
                raise ValueError(
                    f"{n} devices not divisible by fixed axes product {fixed}")
            sizes[free[0]] = n // fixed
        total = int(np.prod(list(sizes.values())))
        if total != n:
            raise ValueError(f"mesh {sizes} wants {total} devices, have {n}")
        return sizes


def make_mesh(spec: Optional[MeshSpec] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh over the given (default: all) devices.

    Axes with size 1 are kept so shardings can always name them — XLA
    elides trivial collectives, so this costs nothing.
    """
    devices = list(devices) if devices is not None else jax.devices()
    spec = spec or MeshSpec()
    sizes = spec.resolve(len(devices))
    axis_names = tuple(sizes)
    dev_array = np.asarray(devices).reshape(tuple(sizes.values()))
    return Mesh(dev_array, axis_names)


def mesh_spec_from_config() -> MeshSpec:
    """The MeshSpec the MMLSPARK_TPU_MESH_* knobs declare (dp x mp)."""
    return MeshSpec(data=int(MESH_DATA.current()),
                    model=int(MESH_MODEL.current()),
                    seq=int(MESH_SEQ.current()))


def default_mesh() -> Mesh:
    """The mesh scoring/training paths get when none is passed explicitly.

    With the MESH knobs at their defaults this is exactly `best_mesh()`
    (dp-only over local devices — the unchanged fast path).  Setting
    `MMLSPARK_TPU_MESH_MODEL=2` (etc.) turns every default-mesh consumer
    — TPUModel scoring, Trainer.fit_arrays, TextGenerator — into a dp x
    mp run without touching call sites: weights follow the partition
    rules (parallel/partition.py), batches stay on the data axis.
    """
    spec = mesh_spec_from_config()
    if spec.model <= 1 and spec.seq <= 1 and spec.data <= 0:
        return best_mesh()
    local = jax.local_devices() if jax.process_count() > 1 else jax.devices()
    if spec.data <= 0:
        sizes = spec.resolve(len(local))
    else:
        sizes = {"data": spec.data, "model": max(spec.model, 1),
                 "seq": max(spec.seq, 1)}
    n = sizes["data"] * sizes["model"] * sizes["seq"]
    if n > len(local):
        raise ValueError(
            f"MMLSPARK_TPU_MESH_DATA x MODEL x SEQ wants {n} devices, "
            f"have {len(local)}")
    return make_mesh(MeshSpec(**sizes), local[:n])


def best_mesh(n_data: Optional[int] = None) -> Mesh:
    """The default 1-D data-parallel mesh (the CNTKModel scoring topology).

    Under multi-host the default spans only this process's devices: scoring
    is embarrassingly parallel over row partitions (the reference's
    per-partition eval loop, CNTKModel.scala:215-221), so each host scores
    its local rows with no cross-host collectives or lockstep batching.
    Training meshes (which DO span hosts) are built explicitly via
    `make_mesh`.
    """
    local = jax.local_devices() if jax.process_count() > 1 else jax.devices()
    if n_data is None:
        return make_mesh(MeshSpec(), local)
    return make_mesh(MeshSpec(data=n_data), local[:n_data])


def batch_sharding(mesh: Mesh, *, axis: str = DATA_AXIS) -> NamedSharding:
    """Sharding for a batch: leading axis split over `axis`, rest replicated."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding — model weights under pure data parallelism.

    Replaces the reference's model-bytes broadcast (CNTKModel.scala:215):
    weights live replicated in HBM instead of being re-deserialized per
    partition.
    """
    return NamedSharding(mesh, P())


def model_sharding(mesh: Mesh, spec: P) -> NamedSharding:
    """Arbitrary weight sharding for tensor-parallel layouts."""
    return NamedSharding(mesh, spec)
