"""Multi-host initialization and collective helpers.

Replaces the reference's external-launcher topology — `mpiexec -n <gpus> cntk
parallelTrain=true` plus a hand-written hostfile
(CommandBuilders.scala:79-117) — with in-process `jax.distributed`: every host
runs the same program, `initialize_distributed` wires the DCN rendezvous, and
all collectives are XLA ops over ICI (intra-slice) / DCN (inter-slice).
There is no separate launcher binary to build: any process manager (GKE,
xmanager, bash over ssh) that starts N identical processes works.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax


@dataclasses.dataclass
class DistributedConfig:
    """Rendezvous config for multi-host (multi-slice) runs.

    Field defaults read the standard JAX env vars so a bare
    `initialize_distributed()` works under any cluster manager that sets
    them; explicit values win (the reference's analogue was the hard-coded
    hostfile at CommandBuilders.scala:95-117 — deliberately more flexible
    here).
    """

    coordinator_address: Optional[str] = None   # "host:port" of process 0
    num_processes: Optional[int] = None
    process_id: Optional[int] = None

    @staticmethod
    def from_env() -> "DistributedConfig":
        from mmlspark_tpu import config
        return DistributedConfig(
            coordinator_address=config.COORDINATOR.current(),
            num_processes=config.NUM_PROCESSES.current(),
            process_id=config.PROCESS_ID.current(),
        )


_initialized = False


def initialize_distributed(config: Optional[DistributedConfig] = None) -> bool:
    """Initialize jax.distributed if a multi-host config is present.

    Returns True when running multi-host, False for single-process (the
    common local / single-slice case, where initialization is unnecessary).
    Safe to call more than once.
    """
    global _initialized
    if _initialized:
        return True
    cfg = config or DistributedConfig.from_env()
    if cfg.coordinator_address is None and cfg.num_processes is None:
        return False
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )
    _initialized = True
    return True


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_coordinator() -> bool:
    return jax.process_index() == 0
