"""Multi-host initialization and collective helpers.

Replaces the reference's external-launcher topology — `mpiexec -n <gpus> cntk
parallelTrain=true` plus a hand-written hostfile
(CommandBuilders.scala:79-117) — with in-process `jax.distributed`: every host
runs the same program, `initialize_distributed` wires the DCN rendezvous, and
all collectives are XLA ops over ICI (intra-slice) / DCN (inter-slice).
There is no separate launcher binary to build: any process manager (GKE,
xmanager, bash over ssh) that starts N identical processes works.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Optional

import jax
import numpy as np


@dataclasses.dataclass
class DistributedConfig:
    """Rendezvous config for multi-host (multi-slice) runs.

    Field defaults read the standard JAX env vars so a bare
    `initialize_distributed()` works under any cluster manager that sets
    them; explicit values win (the reference's analogue was the hard-coded
    hostfile at CommandBuilders.scala:95-117 — deliberately more flexible
    here).
    """

    coordinator_address: Optional[str] = None   # "host:port" of process 0
    num_processes: Optional[int] = None
    process_id: Optional[int] = None

    @staticmethod
    def from_env() -> "DistributedConfig":
        from mmlspark_tpu import config
        return DistributedConfig(
            coordinator_address=config.COORDINATOR.current(),
            num_processes=config.NUM_PROCESSES.current(),
            process_id=config.PROCESS_ID.current(),
        )


_initialized = False


def initialize_distributed(config: Optional[DistributedConfig] = None) -> bool:
    """Initialize jax.distributed if a multi-host config is present.

    Returns True when running multi-host, False for single-process (the
    common local / single-slice case, where initialization is unnecessary).
    Safe to call more than once.
    """
    global _initialized
    if _initialized:
        return True
    cfg = config or DistributedConfig.from_env()
    if cfg.coordinator_address is None and cfg.num_processes is None:
        return False
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )
    _initialized = True
    return True


class CollectiveTimeoutError(RuntimeError):
    """A named collective did not complete within its deadline — some
    participant is dead or wedged.  Carries enough to act on: which
    operation, this process's id, and the full expected-peer set."""

    def __init__(self, op: str, timeout_s: float, present: list[int]):
        everyone = list(range(jax.process_count()))
        missing = sorted(set(everyone) - set(present)) if present else None
        detail = (f"; peers that reached the {op!r} rendezvous: {present}, "
                  f"MISSING: {missing}" if present else
                  f"; expected participants: {everyone}")
        super().__init__(
            f"collective {op!r} timed out after {timeout_s:.0f}s on "
            f"process {jax.process_index()}{detail}. A participant host "
            "is likely dead or wedged — check its logs, then restart the "
            "job (training resumes from the newest checkpoint with "
            "resume=True).")
        self.op = op
        self.timeout_s = timeout_s
        self.present = present
        self.missing = missing


def collective_timeout_s() -> float:
    from mmlspark_tpu import config
    return float(config.COLLECTIVE_TIMEOUT_S.current())


def run_collective(op: str, fn: Callable[[], Any],
                   timeout_s: Optional[float] = None) -> Any:
    """Run a blocking collective with a bounded wait.

    Single-process: calls `fn` directly (nothing to hang on).  Multi-host:
    `fn` runs in a worker thread and the caller waits at most `timeout_s`
    (default MMLSPARK_TPU_COLLECTIVE_TIMEOUT_S); on expiry a
    `CollectiveTimeoutError` NAMES the operation instead of the job
    wedging forever inside an opaque XLA/DCN wait.  The abandoned worker
    thread is daemonic — the process is expected to exit on this error.
    """
    from mmlspark_tpu.observe.trace import trace_event, trace_span
    if jax.process_count() == 1:
        # still spanned: collective call sites (checkpoint gather/broadcast,
        # preempt sync) keep their durations in the run record even when
        # the op degenerates to a local call
        with trace_span(f"collective.{op}", cat="collective", op=op):
            return fn()
    timeout = timeout_s if timeout_s is not None else collective_timeout_s()
    result: dict[str, Any] = {}
    error: list[BaseException] = []

    def run():
        try:
            result["value"] = fn()
        except BaseException as e:  # surfaced to the caller below
            error.append(e)

    worker = threading.Thread(target=run, daemon=True,
                              name=f"collective-{op}")
    worker.start()
    with trace_span(f"collective.{op}", cat="collective", op=op,
                    timeout_s=timeout):
        worker.join(timeout)
    if worker.is_alive():
        from mmlspark_tpu.observe.metrics import inc_counter
        inc_counter("collective.timeouts")
        trace_event("collective.timeout", cat="resilience", op=op,
                    timeout_s=timeout)
        raise CollectiveTimeoutError(op, timeout, present=[])
    if error:
        raise error[0]
    return result["value"]


def barrier(tag: str, timeout_s: Optional[float] = None) -> None:
    """A named, bounded-wait barrier over all processes.

    Place one before a broadcast/gather whose peers might be dead: the
    barrier converts an indefinite hang into a CollectiveTimeoutError
    that names the rendezvous point."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    run_collective(f"barrier:{tag}",
                   lambda: multihost_utils.sync_global_devices(tag),
                   timeout_s)


def health_check(timeout_s: Optional[float] = None) -> list[int]:
    """Allgather every process id with a bounded wait; returns the sorted
    participant list (trivially [0] single-process).  A dead peer turns
    into a CollectiveTimeoutError instead of an infinite stall."""
    if jax.process_count() == 1:
        return [0]
    from jax.experimental import multihost_utils

    def gather():
        ids = multihost_utils.process_allgather(
            np.asarray(jax.process_index()))
        return sorted(int(i) for i in np.asarray(ids).ravel())

    return run_collective("health_check", gather, timeout_s)


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_coordinator() -> bool:
    return jax.process_index() == 0
