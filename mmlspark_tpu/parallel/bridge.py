"""Host table <-> device array bridge.

Replaces the reference's quadruple-copy JNI boundary
(CNTKModel.scala:63-92: Row -> FloatVector -> Value -> evaluate ->
FloatVectorVector -> Row) with a single host->HBM transfer: numpy columns are
`jax.device_put` directly with a NamedSharding, so each device receives only
its shard (no full-batch replication, no per-row copies).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from mmlspark_tpu.parallel.mesh import DATA_AXIS, batch_sharding, replicated


def pad_to_multiple(arr: np.ndarray, multiple: int,
                    axis: int = 0) -> tuple[np.ndarray, int]:
    """Zero-pad `arr` along `axis` to a multiple; returns (padded, valid_count).

    Sharded arrays need a leading dim divisible by the mesh axis; static
    padded shapes also keep XLA from recompiling per remainder batch.
    """
    n = arr.shape[axis]
    rem = n % multiple
    if rem == 0:
        return arr, n
    pad = multiple - rem
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, pad)
    return np.pad(arr, widths), n


def shard_batch(arr: np.ndarray, mesh: Mesh, *, axis: str = DATA_AXIS) -> jax.Array:
    """Place a host batch onto the mesh, split along the leading dim."""
    padded, _ = pad_to_multiple(np.asarray(arr), mesh.shape[axis])
    return jax.device_put(padded, batch_sharding(mesh, axis=axis))


def shard_table_columns(table, columns: Sequence[str], mesh: Mesh,
                        *, axis: str = DATA_AXIS,
                        dtype=None) -> tuple[dict[str, jax.Array], int]:
    """Materialize table columns as sharded device arrays.

    Returns (column dict, valid row count) — rows beyond the count are
    padding introduced for divisibility.
    """
    out: dict[str, jax.Array] = {}
    valid = table.num_rows
    for c in columns:
        col = table[c]
        if col.dtype == object:
            raise TypeError(
                f"column '{c}' is an object column; tensorize it first")
        arr = col.astype(dtype) if dtype is not None else col
        padded, valid = pad_to_multiple(arr, mesh.shape[axis])
        out[c] = jax.device_put(padded, batch_sharding(mesh, axis=axis))
    return out, valid


def put_batch_parts(mesh: Mesh, *arrays: np.ndarray,
                    axis: str = DATA_AXIS) -> tuple:
    """device_put several row-aligned host arrays with the mesh batch
    sharding, one straight-to-sharded transfer each (no default-device
    hop).  Leading dims must already be shard-divisible — callers that
    pad rows carry per-array pad values (a true-length pads with 1, a
    liveness mask with False), so padding stays theirs.  The bucketed
    decode path stages prompts + true lengths + live masks in lockstep."""
    sharding = batch_sharding(mesh, axis=axis)
    for a in arrays:
        if a.shape[0] % mesh.shape[axis]:
            raise ValueError(
                f"leading dim {a.shape[0]} not divisible by the mesh "
                f"'{axis}' axis ({mesh.shape[axis]}); pad rows first "
                f"(pad_to_multiple)")
    return tuple(jax.device_put(a, sharding) for a in arrays)


def put_sharded(local: np.ndarray, sharding: NamedSharding) -> jax.Array:
    """Assemble a global device array from this process's local rows.

    Single-process: a plain `device_put`.  Multi-host (the replacement for
    the reference's per-node MPI data feed, CommandBuilders.scala:95-117):
    every process contributes only the rows its addressable devices hold, and
    `jax.make_array_from_process_local_data` stitches them into one global
    array — no host ever materializes the global batch.
    """
    if jax.process_count() == 1:
        return jax.device_put(local, sharding)
    return jax.make_array_from_process_local_data(sharding, local)


_gather_fns: dict[Mesh, Any] = {}
_GATHER_CACHE_MAX = 8  # a process uses a handful of meshes; bound the cache
# so churning through many short-lived meshes can't pin them (and their
# compiled executables) for the process lifetime


def gather_replicated(tree: Any, mesh: Mesh) -> Any:
    """All-gather a pytree to fully-replicated device arrays.

    Under multi-host, shards owned by other processes are not addressable;
    an XLA identity jit with fully-replicated output shardings performs the
    all-gather over ICI/DCN.  Every process must call this (it is a
    collective).  The jitted gather is cached per mesh so repeated
    checkpoints don't re-lower/re-compile.
    """
    if mesh not in _gather_fns:
        while len(_gather_fns) >= _GATHER_CACHE_MAX:
            _gather_fns.pop(next(iter(_gather_fns)))  # FIFO eviction
        _gather_fns[mesh] = jax.jit(lambda t: t,
                                    out_shardings=replicated(mesh))
    return _gather_fns[mesh](tree)


_snapshot_fn = None


def snapshot_tree(tree: Any) -> Any:
    """A defensive on-device copy with UNCHANGED shardings.

    The async-checkpoint snapshot (train/trainer.py): the writer thread
    device_gets the copy at its leisure, so the step loop donating the
    live state buffers to the next step never invalidates a pending
    write.  Single-process only — every shard is addressable, so no
    replication (cost: one device-local copy of the state bytes, not
    n_devices copies); multi-host saves keep `gather_replicated`, which
    the coordinator needs for addressability anyway.
    """
    global _snapshot_fn
    if _snapshot_fn is None:
        _snapshot_fn = jax.jit(lambda t: t)  # identity jit = fresh buffers
    return _snapshot_fn(tree)


def gather_to_host(tree: Any, mesh: Mesh) -> Any:
    """Fetch a pytree of (possibly cross-process sharded) arrays to host."""
    if jax.process_count() == 1:
        return jax.device_get(tree)
    return jax.device_get(gather_replicated(tree, mesh))


def reshard(x: Any, sharding: NamedSharding) -> jax.Array:
    """Re-lay-out an already-device-resident array (CheckpointData cache
    slices) onto `sharding` — an on-device transfer, never host-bounced
    (unlike `put_sharded`, which assembles from host rows per process)."""
    return jax.device_put(x, sharding)


def put_tree(tree: Any, shardings: Any) -> Any:
    """device_put every leaf onto its matching sharding (cold path: state
    init).  Hot-loop modules use this instead of raw `jax.device_put` —
    scripts/lint.py keeps transfers inside bridge.py/prefetch.py."""
    return jax.tree_util.tree_map(jax.device_put, tree, shardings)


def put_like(new: Any, old: Any, mesh: Optional[Mesh] = None) -> Any:
    """Place `new` with `old`'s sharding (checkpoint restore: host values
    re-committed onto the live state's layout); passthrough when `old`
    carries no sharding (plain host leaves).  With `mesh`, leaves whose
    live sharding is single-device (uncommitted scalars such as optax
    step counters) are committed mesh-replicated instead: copying the
    single-device placement would pin them to the default device, which
    a jitted step rejects when the mesh is a strict subset of the
    process's devices (elastic resume onto fewer chips)."""
    if not hasattr(old, "sharding"):
        return new
    sharding = old.sharding
    if mesh is not None and isinstance(sharding,
                                       jax.sharding.SingleDeviceSharding):
        sharding = replicated(mesh)
    return jax.device_put(new, sharding)


def put_tree_like(new_tree: Any, like_tree: Any,
                  mesh: Optional[Mesh] = None) -> Any:
    """Reshard-on-restore: commit a host pytree onto the shardings of a
    live tree built for the CURRENT mesh.  Checkpoints store gathered
    (full logical shape) arrays, so their global shapes are
    device-count-independent — a state saved under dp=N lands correctly
    on an M-device mesh because the target layout comes from the live
    state, never from the file (elastic resume, train/trainer.py).
    `mesh` promotes single-device leaves to mesh-replicated (put_like)."""
    return jax.tree_util.tree_map(lambda n, o: put_like(n, o, mesh),
                                  new_tree, like_tree)


def replicate_tree(tree: Any, mesh: Mesh) -> Any:
    """Replicate a pytree (model weights) across the mesh."""
    sharding = replicated(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


def stack_trees(trees: Sequence[Any]) -> Any:
    """Stack N structurally-identical host pytrees on a new leading
    population axis (train/sweep.py: member param/opt trees become ONE
    tree whose leaves carry shape (N, ...), so a single vmapped step
    trains every member).  Host-side by design — stacking happens once
    at init/restore, before the tree is committed to devices."""
    if not trees:
        raise ValueError("stack_trees needs at least one tree")
    return jax.tree_util.tree_map(
        lambda *leaves: np.stack([np.asarray(l) for l in leaves]), *trees)


def unstack_member(tree: Any, k: int) -> Any:
    """Slice member `k` out of a population-stacked pytree, returning
    host arrays of the member's unstacked shapes (the sweep winner's
    tree, ready for an ordinary ModelBundle)."""
    return jax.tree_util.tree_map(
        lambda leaf: np.asarray(jax.device_get(leaf))[k], tree)


def device_to_host(x: Any, valid: Optional[int] = None) -> np.ndarray:
    """Fetch a (possibly sharded) device array back to host, trimming padding."""
    arr = np.asarray(jax.device_get(x))
    if valid is not None:
        arr = arr[:valid]
    return arr
