from mmlspark_tpu.parallel.mesh import (
    MeshSpec,
    batch_sharding,
    best_mesh,
    default_mesh,
    make_mesh,
    mesh_spec_from_config,
    replicated,
)
from mmlspark_tpu.parallel.partition import (
    DEFAULT_RULES,
    match_partition_rules,
    make_gather_fns,
    make_shard_fns,
    shard_constraint,
    shard_tree,
    use_mesh,
)
from mmlspark_tpu.parallel.bridge import (
    device_to_host,
    pad_to_multiple,
    shard_batch,
    shard_table_columns,
)
from mmlspark_tpu.parallel.distributed import DistributedConfig, initialize_distributed
from mmlspark_tpu.parallel.prefetch import Prefetcher, default_depth
