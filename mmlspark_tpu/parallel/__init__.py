from mmlspark_tpu.parallel.mesh import (
    MeshSpec,
    batch_sharding,
    best_mesh,
    make_mesh,
    replicated,
)
from mmlspark_tpu.parallel.bridge import (
    device_to_host,
    pad_to_multiple,
    shard_batch,
    shard_table_columns,
)
from mmlspark_tpu.parallel.distributed import DistributedConfig, initialize_distributed
from mmlspark_tpu.parallel.prefetch import Prefetcher, default_depth
