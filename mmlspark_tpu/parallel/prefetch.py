"""Bounded background producer: overlap host work and transfers with compute.

The scoring and training hot loops used to alternate — decode/stack/pad on
the dispatch thread, then a synchronous `device_put`, then the jitted step —
so the MXU idled while the host prepared the next batch.  `Prefetcher` is
the pipelined replacement (the tf.data producer/consumer move,
arXiv:2101.12127): a stage function runs on a small thread pool, at most
`depth` staged batches exist at any moment (backpressure — HBM holds a
bounded number of in-flight batches), and results are handed to the
consumer strictly in submission order, so pipelining never reorders rows.

Contract:

  * **Deterministic ordering** — results come back in item order no matter
    which worker finishes first (a FIFO of futures, not a completion queue).
  * **Backpressure** — at most `depth` items are staged-but-unconsumed; the
    source iterator is never advanced more than `depth` items past the
    consumer.
  * **Exception propagation** — a stage-function error surfaces in the
    consumer at exactly the failed item's position (original exception,
    earlier results undisturbed); a source-iterator error surfaces after
    every already-staged result has been delivered.
  * **Clean shutdown** — `close()` (also via context manager / generator
    teardown) cancels queued work and releases the pool, so a `Preempted`
    or any consumer-side exception never leaks staging threads.

`depth=0` degenerates to a synchronous inline map on the consumer thread —
the "prefetch off" mode bench.py measures against, and the debugging
escape hatch.

The `device_put` half of staging lives here (and in `parallel/bridge.py`)
by design: scripts/lint.py forbids raw `jax.device_put` in the hot-loop
modules, so every host->HBM transfer goes through one of these two files.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, Iterator, Optional

from mmlspark_tpu import config

PREFETCH_DEPTH = config.register(
    "MMLSPARK_TPU_PREFETCH_DEPTH", default=8, ptype=int,
    doc="Default pipeline depth: staged batches in flight per hot loop "
        "(TPUModel scoring window, image-decode lookahead). Positive "
        "values pin the depth; 0 means autotune (the data layer's "
        "Autotuner starts at the DEPTH_FLOOR and resizes from measured "
        "stage timings); -1 disables overlap (synchronous per-batch "
        "round trips — what 0 meant before the autotuner existed).")

PREFETCH_WORKERS = config.register(
    "MMLSPARK_TPU_PREFETCH_WORKERS", default=4, ptype=int,
    doc="Staging thread-pool width per prefetcher (clamped to the depth); "
        "threads run host featurize/pad work and the device_put transfer.")

PREFETCH_WORKER_NS = config.register(
    "MMLSPARK_TPU_DATA_SERVICE_WORKER_NS", default=None,
    doc="Gauge namespace prefix for Prefetcher stages running inside a "
        "data-service worker (set per process by the dispatcher at "
        "spawn, e.g. 'data.service.w3'): stage gauges publish as "
        "'<ns>.<stage>.depth' instead of 'prefetch.<stage>.depth', so "
        "N workers reporting into one metrics backend never collide. "
        "Unset: the in-process 'prefetch.' namespace.")

# The autotuner's floor: an autotuned stage starts here and is never
# narrowed below it, so "autotune" always keeps at least double buffering.
DEPTH_FLOOR = 2


def resolve_depth(value=None) -> tuple:
    """Resolve a depth knob to `(depth, autotune)`.

    The shared knob contract (prefetchDepth Param, TrainerConfig.
    prefetch_depth, MMLSPARK_TPU_PREFETCH_DEPTH): `None` defers to the
    config var; a positive value pins the depth (autotune off); `0`
    requests autotuning, starting from DEPTH_FLOOR; any negative value
    means fully synchronous (depth 0, the debugging escape hatch that
    `0` used to mean).
    """
    if value is None:
        value = int(config.get("MMLSPARK_TPU_PREFETCH_DEPTH"))
    value = int(value)
    if value > 0:
        return value, False
    if value == 0:
        return DEPTH_FLOOR, True
    return 0, False


def default_depth() -> int:
    """The configured pipeline depth (MMLSPARK_TPU_PREFETCH_DEPTH),
    resolved: positive values pass through, 0 (autotune) resolves to the
    DEPTH_FLOOR the autotuner starts from, negative to 0 (synchronous)."""
    return resolve_depth(None)[0]


class Prefetcher:
    """Order-preserving bounded background map over an item iterator.

        with Prefetcher(stage_fn, plans, depth=8) as staged:
            for result in staged:
                consume(result)

    `stage_fn(item)` runs on worker threads; iteration yields
    `stage_fn(item)` for every item, in item order.
    """

    def __init__(self, fn: Callable[[Any], Any], items: Iterable,
                 *, depth: int, workers: Optional[int] = None,
                 max_depth: Optional[int] = None, name: str = "prefetch"):
        self._closed = False  # first: __del__ runs even if init raises
        if depth < 0:
            raise ValueError(f"prefetch depth must be >= 0, got {depth}")
        self._fn = fn
        self._items = iter(items)
        self._depth = int(depth)
        # `max_depth` reserves headroom for live retuning: the pool is
        # sized for the cap, so `set_depth()` can widen a running stage
        # without rebuilding threads (the data-layer Autotuner's lever).
        self._max_depth = (max(self._depth, int(max_depth))
                           if max_depth is not None else self._depth)
        if workers is None:
            workers = int(config.get("MMLSPARK_TPU_PREFETCH_WORKERS"))
        self._workers = max(1, min(int(workers), self._max_depth or 1))
        self._name = name
        self._pending: deque = deque()   # futures, submission order
        self._executor: Optional[ThreadPoolExecutor] = None
        self._source_error: Optional[BaseException] = None
        self._exhausted = False
        # telemetry (observe/telemetry.py): the run handle is captured at
        # construction — on the CONSUMER thread — because workers never
        # see its contextvar.  When a run is active, each delivery gauges
        # the staged-queue depth and the cumulative time the consumer
        # spent BLOCKED on an unfinished staging future (stall = the
        # pipeline failing to hide host/transfer work).
        from mmlspark_tpu.observe.telemetry import active_run
        self._run = active_run()
        # inside a data-service worker, gauges carry the per-worker
        # namespace the dispatcher assigned (data.service.w<k>.<stage>)
        # so fleet members never collide on one metrics backend
        ns = config.get("MMLSPARK_TPU_DATA_SERVICE_WORKER_NS")
        self._gauge_ns = f"{ns}.{name}" if ns else f"prefetch.{name}"
        # always-on counters (cheap: one perf_counter pair per stalled
        # pull) — the data-layer Autotuner reads these via `stats()` even
        # when no telemetry run is active
        self.stall_s = 0.0
        self.stalls = 0      # deliveries that blocked on an unfinished future
        self.deliveries = 0  # results handed to the consumer
        self.residency = 0   # sum of staged-queue length at each delivery

    # -- tuning ---------------------------------------------------------
    @property
    def depth(self) -> int:
        return self._depth

    @property
    def max_depth(self) -> int:
        return self._max_depth

    def set_depth(self, depth: int) -> int:
        """Retune the staged window live, clamped to [1, max_depth];
        returns the depth actually applied.  A synchronous prefetcher
        (max_depth 0) has no window to tune and stays at 0."""
        if self._max_depth <= 0:
            return 0
        self._depth = max(1, min(int(depth), self._max_depth))
        return self._depth

    def stats(self) -> dict:
        """Counter snapshot for the autotuner (window deltas are the
        caller's job): deliveries, stalls, stall_s, residency, depth."""
        return {"deliveries": self.deliveries, "stalls": self.stalls,
                "stall_s": self.stall_s, "residency": self.residency,
                "depth": self._depth, "max_depth": self._max_depth}

    # -- iteration ------------------------------------------------------
    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        if self._depth == 0:
            # synchronous mode: no threads, no lookahead — the item is
            # pulled, staged, and returned on the consumer thread
            try:
                item = next(self._items)
            except StopIteration:
                self.close()
                raise
            result = self._fn(item)
            self.deliveries += 1
            return result
        try:
            self._top_up()
            if not self._pending:
                if self._source_error is not None:
                    err, self._source_error = self._source_error, None
                    raise err
                self.close()
                raise StopIteration
            fut = self._pending.popleft()
            stalled = not fut.done()
            t0 = time.perf_counter() if stalled else 0.0
            result = fut.result()
            if stalled:
                self.stall_s += time.perf_counter() - t0
                self.stalls += 1
            self.deliveries += 1
            self.residency += len(self._pending)
            if self._run is not None:
                self._run.gauge(f"{self._gauge_ns}.depth",
                                len(self._pending))
                self._run.gauge(f"{self._gauge_ns}.stall_s",
                                round(self.stall_s, 6))
            self._top_up()  # refill the window before handing control back
            return result
        except StopIteration:
            raise
        except BaseException:
            self.close()
            raise

    def _top_up(self) -> None:
        """Keep `depth` items staged; source errors are deferred until the
        already-staged results have been delivered (ordering contract)."""
        while (not self._exhausted and self._source_error is None
                and len(self._pending) < self._depth):
            try:
                item = next(self._items)
            except StopIteration:
                self._exhausted = True
                break
            except BaseException as e:  # source iterator failed
                self._source_error = e
                break
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=self._workers,
                    thread_name_prefix=f"mmlspark-{self._name}")
            self._pending.append(self._executor.submit(self._fn, item))

    # -- lifecycle ------------------------------------------------------
    def close(self) -> None:
        """Cancel queued work and release the pool (idempotent; safe even
        when __init__ raised before the queues existed)."""
        if self._closed or not hasattr(self, "_pending"):
            return
        self._closed = True
        for fut in self._pending:
            fut.cancel()
        self._pending.clear()
        if self._executor is not None:
            self._executor.shutdown(wait=False, cancel_futures=True)
            self._executor = None

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        self.close()


class OncePerTable:
    """Thread-safe lazy computation shared by one table's staged batches.

    The per-table host conversion (`TPUModel._tensor_column`'s np.stack)
    must run ONCE even when several of the table's batches stage
    concurrently on different workers; whichever worker arrives first pays
    the cost and the rest reuse the value.
    """

    def __init__(self, compute: Callable[[], Any]):
        self._compute = compute
        self._lock = threading.Lock()
        self._value = None
        self._done = False

    def get(self) -> Any:
        if self._done:  # fast path: no lock once materialized
            return self._value
        with self._lock:
            if not self._done:
                self._value = self._compute()
                self._done = True
        return self._value
