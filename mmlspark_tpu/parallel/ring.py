"""Sequence-parallel execution: shard_map wrappers + LM train step.

The distributed face of ops/attention.py: sequences too long for one
device's HBM shard over the mesh `seq` axis; ring attention rotates K/V
blocks over ICI neighbor links (ppermute — the bandwidth-optimal pattern
for this topology) while Ulysses trades two all-to-alls for local dense
attention.  Everything composes with data parallelism: batch over `data`,
sequence over `seq`, weights replicated (TP composes via the trainer's
kernel sharding rule).

The reference has no analogue (SURVEY §5 "long-context: absent") — this is
the first-class long-context support the TPU build adds.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from mmlspark_tpu.ops.attention import attention, ring_attention, ulysses_attention
from mmlspark_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS
from mmlspark_tpu.parallel.partition import named_sharding

try:  # jax >= 0.8 top-level API; the experimental path is deprecated
    from jax import shard_map as _shard_map_raw
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_raw


def _shard_map(fn, *, mesh, in_specs, out_specs):
    """`shard_map` with replication checking off — the repo-wide seam.

    The replication checker has no rule for `checkpoint_name` (the remat
    tag the seq-parallel LM forward emits) or `pallas_call` (the flash
    kernel ring_flash rotates) on the pinned jax build, so every sharded
    region here runs unchecked: out_specs state the replication facts the
    checker would otherwise verify.  The kwarg spelling moved across jax
    versions (`check_rep` -> `check_vma`), so probe newest-first and fall
    through to a bare call on builds that dropped the knob entirely.
    """
    for kwarg in ("check_vma", "check_rep"):
        try:
            return _shard_map_raw(fn, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, **{kwarg: False})
        except TypeError:
            continue
    return _shard_map_raw(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs)


def seq_parallel_attention(mesh: Mesh, q, k, v, causal: bool = False,
                           impl: str = "ring",
                           data_axis: str = DATA_AXIS,
                           seq_axis: str = SEQ_AXIS):
    """Attention over (B, S, H, D) arrays with S sharded over `seq_axis`.

    A standalone entry point for scoring paths; training integrates via
    make_seq_parallel_lm_step (the model's attention runs inside the same
    shard_map region as the loss).
    """
    if impl == "ring":
        fn = functools.partial(ring_attention, axis_name=seq_axis,
                               causal=causal)
    elif impl == "ring_flash":
        from mmlspark_tpu.ops.attention import ring_flash_attention
        fn = functools.partial(ring_flash_attention, axis_name=seq_axis,
                               causal=causal)
    elif impl == "ulysses":
        fn = functools.partial(ulysses_attention, axis_name=seq_axis,
                               causal=causal)
    elif impl == "dense":
        # all-gather the sequence axis; correctness fallback
        def fn(ql, kl, vl):
            kg = jax.lax.all_gather(kl, seq_axis, axis=1, tiled=True)
            vg = jax.lax.all_gather(vl, seq_axis, axis=1, tiled=True)
            start = jax.lax.axis_index(seq_axis) * ql.shape[1]
            return attention(ql, kg, vg, causal=causal, q_offset=start)
    else:
        raise ValueError(f"unknown seq-parallel impl '{impl}'")

    spec = P(data_axis, seq_axis, None, None)
    wrapped = _shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)
    return wrapped(q, k, v)


def make_seq_parallel_lm_step(module, tx: optax.GradientTransformation,
                              mesh: Mesh,
                              data_axis: str = DATA_AXIS,
                              seq_axis: str = SEQ_AXIS,
                              remat: bool = False) -> Callable:
    """Build a jitted LM train step with batch over `data` and sequence
    over `seq`.

    The whole loss runs inside one shard_map region: the module (a
    TransformerLM with attn='ring'|'ulysses' and seq_axis set) computes
    ring attention with the axis in scope, per-token losses are averaged
    with psum over both axes, and jax.grad differentiates straight through
    the collectives (ppermute/psum have registered transposes).  Params
    and optimizer state stay replicated.

    `remat=True` turns on block-boundary activation rematerialization
    (the module's own `remat` field — each TransformerBlock recomputes its
    activations in the backward): inside the ring loop that is the 32k+
    story, since the per-fold score blocks are what blow HBM at long
    S_local.  The `checkpoint_name` tags this emits inside the sharded
    region are exactly why `_shard_map` runs with replication checking
    off.
    """
    if remat and getattr(module, "remat", None) is False:
        module = module.clone(remat=True)

    def local_loss(params, tokens, targets, mask):
        logits = module.apply(params, tokens)          # (b_l, s_l, V)
        ll = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), targets)
        total = jax.lax.psum((ll * mask).sum(), (data_axis, seq_axis))
        denom = jax.lax.psum(mask.sum(), (data_axis, seq_axis))
        return total / jnp.maximum(denom, 1.0)

    tok_spec = P(data_axis, seq_axis)

    sharded_loss = _shard_map(
        local_loss, mesh=mesh,
        in_specs=(P(), tok_spec, tok_spec, tok_spec),
        out_specs=P())

    @jax.jit
    def step(params, opt_state, tokens, targets, mask):
        loss, grads = jax.value_and_grad(
            lambda p: sharded_loss(p, tokens, targets, mask))(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def shard_tokens(tokens: np.ndarray, mesh: Mesh,
                 data_axis: str = DATA_AXIS,
                 seq_axis: str = SEQ_AXIS) -> jax.Array:
    """Place (B, S) token arrays with B over data, S over seq.

    Placement routes through `parallel/partition.named_sharding` — the
    one sanctioned NamedSharding construction seam (scripts/lint.py)."""
    return jax.device_put(
        tokens, named_sharding(mesh, P(data_axis, seq_axis)))
