"""Pipeline parallelism (PP): GPipe-style microbatched stage pipeline.

New-design headroom over the reference (whose only model distribution was
a broadcast copy per executor — SURVEY §2b): the transformer block stack
is partitioned over a mesh axis, one stage per device group, and
microbatches flow through the ring.

TPU-first mechanics, all inside one `shard_map`:

  * layer params are STACKED on a leading layer dim and sharded over the
    stage axis, so each device holds only its own stage's weights — the
    memory win that motivates PP;
  * the schedule is a `lax.scan` over `n_micro + n_stages - 1` ticks; at
    each tick every stage applies its layers to its current activation
    and `ppermute`s the result one hop down the ring (stage 0 injects a
    fresh microbatch, the last stage banks its finished one).  Bubble
    fraction is the usual (S-1)/(M+S-1);
  * the BACKWARD schedule is not hand-written: jax differentiates through
    scan + ppermute, producing the reverse pipeline automatically (XLA
    transposes ppermute into the opposite rotation).

Embedding / final-norm / LM-head stay replicated (they are a sliver of
the FLOPs); the data axis composes orthogonally — tokens shard over
'data' while stages ride the stage axis, so dp x pp runs in one jit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from mmlspark_tpu.models.definitions import TransformerBlock
from mmlspark_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from mmlspark_tpu.parallel.ring import _shard_map


def init_pipelined_lm(rng, *, vocab_size: int, d_model: int, n_heads: int,
                      n_layers: int, max_len: int, mlp_ratio: int = 4,
                      dtype=jnp.float32) -> dict:
    """Parameter tree for the pipelined LM: block params stacked on a
    leading layer dim (leaves (L, ...)), plus replicated embed/norm/head."""
    block = TransformerBlock(d_model, n_heads, mlp_ratio, dtype)
    x = jnp.zeros((1, max_len, d_model), dtype)
    keys = jax.random.split(rng, n_layers + 2)
    stacked = jax.vmap(
        lambda k: block.init(k, x)["params"])(keys[:n_layers])
    k_e, k_h = keys[n_layers], keys[n_layers + 1]
    scale = d_model ** -0.5
    return {
        "tok_embed": jax.random.normal(k_e, (vocab_size, d_model)) * scale,
        "pos_embed": jax.random.normal(
            jax.random.fold_in(k_e, 1), (max_len, d_model)) * scale,
        "blocks": stacked,
        "norm_scale": jnp.ones((d_model,)),
        "norm_bias": jnp.zeros((d_model,)),
        "head": jax.random.normal(k_h, (d_model, vocab_size)) * scale,
        "head_bias": jnp.zeros((vocab_size,)),
    }


def _embed(params, tokens):
    x = params["tok_embed"][tokens] + params["pos_embed"][: tokens.shape[1]]
    return x


def _head(params, x):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    x = (x - mu) * jax.lax.rsqrt(var + 1e-6)
    x = x * params["norm_scale"] + params["norm_bias"]
    z = x @ params["head"]
    if "head_bias" in params:
        z = z + params["head_bias"]
    return z


def _apply_stage(block: TransformerBlock, local_blocks, x,
                 remat: bool = False):
    """Apply this stage's stacked layers (L_local, ...) sequentially.

    With `remat`, each layer's activations are rematerialized in the
    backward (jax.checkpoint per scan step) — the standard memory lever
    when a stage holds many layers."""
    def body(h, layer_params):
        return block.apply({"params": layer_params}, h), None
    if remat:
        # scan already prevents the unsound CSE; the default True would
        # insert needless optimization barriers on TPU
        body = jax.checkpoint(body, prevent_cse=False)
    out, _ = lax.scan(body, x, local_blocks)
    return out


def _pipeline_blocks(block, local_blocks, x, stage_axis: str, n_micro: int,
                     remat: bool = False):
    """The GPipe schedule proper (runs inside shard_map)."""
    n_stages = lax.psum(1, stage_axis)
    idx = lax.axis_index(stage_axis)
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} must divide into n_micro={n_micro}")
    mb = b // n_micro
    xs = x.reshape(n_micro, mb, *x.shape[1:])
    ticks = n_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        buf, outs = carry
        # stage 0 injects microbatch t; later stages consume the ring buf.
        # Ticks past the last injection re-feed a stale microbatch whose
        # results never reach a valid output slot (they would arrive after
        # the final tick), so no masking of the compute itself is needed.
        cur = jnp.where(idx == 0, xs[jnp.clip(t, 0, n_micro - 1)], buf)
        y = _apply_stage(block, local_blocks, cur, remat)
        m = t - (n_stages - 1)
        valid = (m >= 0) & (idx == n_stages - 1)
        mclip = jnp.clip(m, 0, n_micro - 1)
        outs = outs.at[mclip].set(jnp.where(valid, y, outs[mclip]))
        buf = lax.ppermute(y, stage_axis, perm)
        return (buf, outs), None

    # the carry becomes stage-varying inside the loop (y depends on this
    # stage's weights), so its initial value must carry that
    # varying-manual-axes type too (the shard_map scan rule)
    mark = lambda a: lax.pcast(a, (stage_axis,), to="varying")
    carry0 = (mark(jnp.zeros_like(xs[0])), mark(jnp.zeros_like(xs)))
    (_, outs), _ = lax.scan(tick, carry0, jnp.arange(ticks))
    # finished activations live on the last stage; replicate them around
    # the ring so the (replicated) head runs everywhere
    outs = lax.psum(jnp.where(idx == n_stages - 1, outs, 0.0), stage_axis)
    return outs.reshape(b, *x.shape[1:])


def pipelined_lm_apply(mesh, params, tokens, *, n_heads: int,
                       n_micro: int = 4, stage_axis: str = MODEL_AXIS,
                       mlp_ratio: int = 4, dtype=jnp.float32,
                       remat: bool = False):
    """Forward logits through the dp x pp mesh (jit-compatible)."""
    d_model = params["norm_scale"].shape[0]
    block = TransformerBlock(d_model, n_heads, mlp_ratio, dtype)

    def fn(p, t):
        x = _embed(p, t).astype(dtype)
        x = _pipeline_blocks(block, p["blocks"], x, stage_axis, n_micro,
                             remat)
        return _head(p, x.astype(jnp.float32))

    blocks_spec = jax.tree_util.tree_map(
        lambda _: P(stage_axis), params["blocks"])
    in_spec = {**{k: P() for k in params}, "blocks": blocks_spec}
    return _shard_map(fn, mesh=mesh,
                      in_specs=(in_spec, P(DATA_AXIS)),
                      out_specs=P(DATA_AXIS))(params, tokens)


def sequential_lm_apply(params, tokens, *, n_heads: int, mlp_ratio: int = 4,
                        dtype=jnp.float32):
    """Single-device reference: same params, plain sequential block stack
    (the parity oracle for the pipeline schedule)."""
    d_model = params["norm_scale"].shape[0]
    block = TransformerBlock(d_model, n_heads, mlp_ratio, dtype)
    x = _embed(params, tokens).astype(dtype)
    x = _apply_stage(block, params["blocks"], x)
    return _head(params, x.astype(jnp.float32))


def pipeline_param_shardings(mesh, params, stage_axis: str = MODEL_AXIS):
    """NamedShardings placing each leaf where the pipeline uses it:
    stacked block layers split over the stage axis, the rest replicated."""
    blocks = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P(stage_axis)), params["blocks"])
    return {**{k: NamedSharding(mesh, P()) for k in params
               if k != "blocks"}, "blocks": blocks}


def make_pipeline_lm_step(mesh, tx, *, n_heads: int, n_micro: int = 4,
                          stage_axis: str = MODEL_AXIS,
                          mlp_ratio: int = 4,
                          dtype=jnp.float32):
    """Jitted (params, opt_state, tokens, targets) -> (params, opt, loss)
    train step through the pipeline (dp over 'data', pp over the stage
    axis); gradients flow through the reverse pipeline automatically."""

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens, targets):
        def loss_fn(p):
            logits = pipelined_lm_apply(
                mesh, p, tokens, n_heads=n_heads, n_micro=n_micro,
                stage_axis=stage_axis, mlp_ratio=mlp_ratio, dtype=dtype)
            lp = jax.nn.log_softmax(logits.astype(jnp.float32))
            return -jnp.take_along_axis(lp, targets[..., None], -1).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        import optax
        return optax.apply_updates(params, updates), opt_state, loss

    return step


def count_pipeline_bubble(n_micro: int, n_stages: int) -> float:
    """Idle fraction of the GPipe schedule: (S-1)/(M+S-1)."""
    return (n_stages - 1) / (n_micro + n_stages - 1)


# --------------------------------------------------------------------------
# Bundle interop: the pipeline's stacked tree <-> TransformerLM flax
# variables.  This is what makes PP a PRODUCT feature rather than a library
# demo: Trainer.fit trains through the pipeline, then emits an ordinary
# TransformerLM ModelBundle that TPUModel scores and later fits warm-start
# from (the reference exposed parallel training behind one config flag,
# CommandBuilders.scala:79-93 — pipeline_stages is ours).
# --------------------------------------------------------------------------

def pipeline_params_from_variables(variables: dict, n_layers: int) -> dict:
    """TransformerLM flax variables -> the pipeline's stacked param tree
    (blocks stacked on a leading layer dim, raw embed/norm/head leaves)."""
    p = variables["params"]
    blocks = [p[f"block{i}_w"] for i in range(n_layers)]
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *blocks)
    return {
        "tok_embed": jnp.asarray(p["tok_embed"]["embedding"]),
        "pos_embed": jnp.asarray(p["pos_embed"]["embedding"]),
        "blocks": stacked,
        "norm_scale": jnp.asarray(p["final_norm_w"]["scale"]),
        "norm_bias": jnp.asarray(p["final_norm_w"]["bias"]),
        "head": jnp.asarray(p["lm_head"]["kernel"]),
        "head_bias": jnp.asarray(p["lm_head"]["bias"]),
    }


def variables_from_pipeline_params(params: dict, n_layers: int) -> dict:
    """The inverse of `pipeline_params_from_variables`: unstack the layer
    dim back into block{i}_w entries of a TransformerLM variables dict."""
    flax_params = {
        "tok_embed": {"embedding": params["tok_embed"]},
        "pos_embed": {"embedding": params["pos_embed"]},
        "final_norm_w": {"scale": params["norm_scale"],
                         "bias": params["norm_bias"]},
        "lm_head": {"kernel": params["head"],
                    "bias": params["head_bias"]},
    }
    for i in range(n_layers):
        flax_params[f"block{i}_w"] = jax.tree_util.tree_map(
            lambda leaf: leaf[i], params["blocks"])
    return {"params": flax_params}
