"""Tree learners as histogram-based XLA programs.

The reference's TrainClassifier/Regressor dispatch to Spark MLlib's
DecisionTree/RandomForest/GBT learners (TrainClassifier.scala:75-77) —
JVM recursion over row partitions.  Trees are the SURVEY's flagged hard
part for TPU: XLA wants static shapes and no data-dependent recursion.
The design here:

  * features are quantile-binned once to int bins (maxBins, default 32);
  * every tree is a COMPLETE binary tree of static depth D — "no split"
    is encoded as a send-everything-left split, so tree traversal is a
    fixed D-step gather loop, and growth is a fixed D-level loop;
  * each level builds (feature x node x bin) gradient/hessian histograms
    with one segment_sum per feature (vmapped) — the classic LightGBM/
    XGBoost histogram trick, batched so the MXU/VPU stays fed;
  * split gain is the XGBoost Newton gain; leaves take -G/(H+lambda).

Boosting (GBT) wraps tree-building with logistic/squared-loss gradients;
forests (RF) bag Poisson row weights + feature subsets; a decision tree
is a forest of one.  Binary GBT only, as the reference
(TrainClassifier.scala:101-104 throws on multiclass GBT); multiclass
DT/RF use per-class probability trees.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import Estimator
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.ml.learners import (ClassifierModel, RegressorModel,
                                      _features_matrix, _sigmoid)


# --------------------------------------------------------------------------
# binning
# --------------------------------------------------------------------------

def quantile_bin_edges(X: np.ndarray, max_bins: int) -> np.ndarray:
    """Per-feature quantile edges, (F, max_bins-1).

    Mirrors MLlib's quantile-based continuous-feature binning (its maxBins
    param has the same meaning)."""
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    edges = np.quantile(X, qs, axis=0).T.astype(np.float32)  # (F, B-1)
    return edges


@jax.jit
def bin_features(X: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """(n, F) float -> (n, F) int32 bin ids via per-feature searchsorted."""
    return jax.vmap(lambda col, e: jnp.searchsorted(e, col),
                    in_axes=(1, 0), out_axes=1)(X, edges).astype(jnp.int32)


# --------------------------------------------------------------------------
# single-tree build + predict (jitted, static depth/bins)
# --------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(3, 4))
def build_tree(binned, grad, hess, depth: int, n_bins: int,
               lam: float = 1.0, feature_mask=None):
    """Grow one complete tree of `depth` levels.

    binned: (n, F) int32; grad/hess: (n,) float32 (zero-weight rows simply
    contribute nothing).  Returns (split_feature (I,), split_bin (I,),
    leaf_value (2**depth,)) with I = 2**depth - 1 internal nodes laid out
    heap-style; "no split" is (feature 0, bin n_bins) => all rows go left.
    """
    n, F = binned.shape
    n_internal = 2 ** depth - 1
    split_feature = jnp.zeros(n_internal, jnp.int32)
    split_bin = jnp.full(n_internal, n_bins, jnp.int32)
    node_of_row = jnp.zeros(n, jnp.int32)       # heap index of each row

    for d in range(depth):
        level_size = 2 ** d
        first = level_size - 1
        local = node_of_row - first              # 0..level_size-1
        seg = local * n_bins                     # base segment per node

        def hists(col):
            idx = seg + col
            hg = jax.ops.segment_sum(grad, idx, level_size * n_bins)
            hh = jax.ops.segment_sum(hess, idx, level_size * n_bins)
            return hg.reshape(level_size, n_bins), hh.reshape(level_size, n_bins)

        hg, hh = jax.vmap(hists, in_axes=1)(binned)   # (F, nodes, bins)
        GL = jnp.cumsum(hg, axis=-1)
        HL = jnp.cumsum(hh, axis=-1)
        G = GL[..., -1:]
        H = HL[..., -1:]
        GR, HR = G - GL, H - HL

        def score(g, h):
            return g * g / (h + lam)

        gain = score(GL, HL) + score(GR, HR) - score(G, H)   # (F, nodes, bins)
        # a split at bin b sends bins <= b left; the last bin is no-split
        gain = gain.at[..., -1].set(-jnp.inf)
        # empty children are useless splits
        gain = jnp.where((HL <= 0) | (HR <= 0), -jnp.inf, gain)
        if feature_mask is not None:
            gain = jnp.where(feature_mask[:, None, None], gain, -jnp.inf)

        flat = gain.transpose(1, 0, 2).reshape(level_size, F * n_bins)
        best = jnp.argmax(flat, axis=1)                       # (nodes,)
        best_gain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
        feat = (best // n_bins).astype(jnp.int32)
        b = (best % n_bins).astype(jnp.int32)
        do_split = best_gain > 1e-12
        feat = jnp.where(do_split, feat, 0)
        b = jnp.where(do_split, b, n_bins)                    # no-split: left

        split_feature = jax.lax.dynamic_update_slice(split_feature, feat,
                                                     (first,))
        split_bin = jax.lax.dynamic_update_slice(
            split_bin, b.astype(jnp.int32), (first,))

        row_feat = feat[local]
        row_thr = b[local]
        go_right = binned[jnp.arange(n), row_feat] > row_thr
        node_of_row = 2 * node_of_row + 1 + go_right.astype(jnp.int32)

    leaf_local = node_of_row - n_internal
    n_leaves = 2 ** depth
    leaf_g = jax.ops.segment_sum(grad, leaf_local, n_leaves)
    leaf_h = jax.ops.segment_sum(hess, leaf_local, n_leaves)
    leaf_value = -leaf_g / (leaf_h + lam)
    return split_feature, split_bin, leaf_value


@functools.partial(jax.jit, static_argnums=(4,))
def predict_tree(binned, split_feature, split_bin, leaf_value, depth: int):
    """(n, F) bins -> (n,) leaf values in `depth` gather steps."""
    n = binned.shape[0]
    node = jnp.zeros(n, jnp.int32)
    for _ in range(depth):
        feat = split_feature[node]
        thr = split_bin[node]
        go_right = binned[jnp.arange(n), feat] > thr
        node = 2 * node + 1 + go_right.astype(jnp.int32)
    return leaf_value[node - (2 ** depth - 1)]


# --------------------------------------------------------------------------
# ensembles
# --------------------------------------------------------------------------

class TreeEnsemble:
    """Bins + a list of (feature, bin, leaf) arrays + a bias."""

    def __init__(self, edges: np.ndarray, depth: int, bias: float = 0.0):
        self.edges = np.asarray(edges, np.float32)
        self.depth = depth
        self.bias = float(bias)
        self.trees: list[tuple[np.ndarray, np.ndarray, np.ndarray]] = []

    def add(self, feature, bins, leaves, weight: float = 1.0):
        self.trees.append((np.asarray(feature), np.asarray(bins),
                           np.asarray(leaves) * weight))

    def bin(self, X: np.ndarray) -> jnp.ndarray:
        return bin_features(jnp.asarray(X, jnp.float32),
                            jnp.asarray(self.edges))

    def raw_predict(self, X: np.ndarray, binned=None) -> np.ndarray:
        if binned is None:
            binned = self.bin(X)
        out = np.full(len(X), self.bias, np.float32)
        for f, b, l in self.trees:
            out += np.asarray(predict_tree(binned, jnp.asarray(f),
                                           jnp.asarray(b), jnp.asarray(l),
                                           self.depth))
        return out

    def save(self, path: str, name: str):
        arrs = {"edges": self.edges, "bias": np.float32(self.bias),
                "depth": np.int32(self.depth),
                "n_trees": np.int32(len(self.trees))}
        for i, (f, b, l) in enumerate(self.trees):
            arrs[f"f{i}"] = f
            arrs[f"b{i}"] = b
            arrs[f"l{i}"] = l
        np.savez(os.path.join(path, f"{name}.npz"), **arrs)

    @staticmethod
    def load(path: str, name: str) -> "TreeEnsemble":
        d = np.load(os.path.join(path, f"{name}.npz"))
        ens = TreeEnsemble(d["edges"], int(d["depth"]), float(d["bias"]))
        for i in range(int(d["n_trees"])):
            ens.trees.append((d[f"f{i}"], d[f"b{i}"], d[f"l{i}"]))
        return ens


def _fit_boosted(X, y, *, depth, n_bins, n_trees, step, lam, loss,
                 row_weights=None, feature_masks=None, boost=True,
                 prebinned=None):
    """Generic tree-ensemble loop; one jitted build per round.

    boost=True: gradients from the running prediction (GBT).
    boost=False: gradients always from the bias — trees are independent
    fits of (y - bias), so step=1/T yields forest averaging (RF/DT).
    `prebinned=(edges, binned)` skips the quantile/binning pass (shared
    across the per-class ensembles of a multiclass forest).
    """
    if prebinned is not None:
        edges, binned = prebinned
    else:
        edges = quantile_bin_edges(X, n_bins)
        binned = bin_features(jnp.asarray(X, jnp.float32), jnp.asarray(edges))
    yj = jnp.asarray(y, jnp.float32)
    w = (jnp.asarray(row_weights, jnp.float32)
         if row_weights is not None else None)

    if loss == "logistic":
        bias = 0.0
    else:
        bias = float(np.mean(y)) if len(y) else 0.0
    ens = TreeEnsemble(edges, depth, bias)
    pred = jnp.full(len(y), bias, jnp.float32)

    for t in range(n_trees):
        if loss == "logistic":
            p = jax.nn.sigmoid(pred)
            grad, hess = p - yj, p * (1 - p)
        else:
            grad, hess = pred - yj, jnp.ones_like(pred)
        if w is not None:
            wt = w if w.ndim == 1 else w[t]
            grad, hess = grad * wt, hess * wt
        mask = (jnp.asarray(feature_masks[t])
                if feature_masks is not None else None)
        f, b, l = build_tree(binned, grad, hess, depth, n_bins, lam, mask)
        ens.add(f, b, l, weight=step)
        if boost:
            pred = pred + step * predict_tree(binned, f, b, l, depth)
    return ens


def _subset_size(n_feats: int, strategy: str) -> int:
    """Features per tree (Spark featureSubsetStrategy vocabulary)."""
    if strategy in ("sqrt", "auto"):
        k = int(np.sqrt(n_feats))
    elif strategy == "log2":
        k = int(np.log2(max(n_feats, 2)))
    elif strategy == "onethird":
        k = n_feats // 3
    else:
        k = int(n_feats * float(strategy))
    return min(max(k, 1), n_feats)


def _valid_strategy(v: str) -> bool:
    if v in ("all", "sqrt", "auto", "log2", "onethird"):
        return True
    try:
        return 0.0 < float(v) <= 1.0
    except ValueError:
        return False


def _bagging(n_rows, n_feats, n_trees, subsample, feat_strategy, rng):
    """Poisson row weights + per-tree feature masks (static shapes)."""
    weights = rng.poisson(subsample, size=(n_trees, n_rows)).astype(np.float32)
    if feat_strategy == "all" or n_feats <= 1:
        masks = np.ones((n_trees, n_feats), bool)
    else:
        k = _subset_size(n_feats, feat_strategy)
        masks = np.zeros((n_trees, n_feats), bool)
        for t in range(n_trees):
            masks[t, rng.choice(n_feats, size=k, replace=False)] = True
    return weights, masks


# --------------------------------------------------------------------------
# models
# --------------------------------------------------------------------------

class TreeClassifierModel(ClassifierModel):
    """Per-class probability ensembles (DT/RF) or a logit ensemble (GBT)."""

    def __init__(self, ensembles: Optional[list] = None,
                 mode: str = "prob", **kw):
        super().__init__(**kw)
        self._ensembles = list(ensembles or [])
        self._mode = mode  # "prob" (leaf-mean trees) | "logit" (boosted)

    @property
    def num_classes(self) -> int:
        return max(len(self._ensembles), 2)

    def _score(self, X):
        if self._mode == "logit":
            z = self._ensembles[0].raw_predict(X)
            p = np.asarray(_sigmoid(jnp.asarray(z)))
            prob = np.stack([1 - p, p], 1)
            raw = np.stack([-z, z], 1)
            return raw, prob, (p > 0.5).astype(np.float64)
        # all per-class ensembles share edges: bin once
        binned = self._ensembles[0].bin(X)
        raw = np.stack([e.raw_predict(X, binned=binned)
                        for e in self._ensembles], 1)
        clipped = np.clip(raw, 1e-6, 1.0)
        prob = clipped / clipped.sum(1, keepdims=True)
        return raw, prob, np.argmax(raw, 1).astype(np.float64)

    def _save_extra(self, path):
        with open(os.path.join(path, "mode.txt"), "w") as f:
            f.write(f"{self._mode}\n{len(self._ensembles)}")
        for i, e in enumerate(self._ensembles):
            e.save(path, f"ens{i}")

    def _load_extra(self, path):
        with open(os.path.join(path, "mode.txt")) as f:
            self._mode, n = f.read().split("\n")
        self._ensembles = [TreeEnsemble.load(path, f"ens{i}")
                           for i in range(int(n))]


class TreeRegressorModel(RegressorModel):
    def __init__(self, ensemble: Optional[TreeEnsemble] = None, **kw):
        super().__init__(**kw)
        self._ensemble = ensemble

    def _predict(self, X):
        return self._ensemble.raw_predict(X)

    def _save_extra(self, path):
        self._ensemble.save(path, "ens")

    def _load_extra(self, path):
        self._ensemble = TreeEnsemble.load(path, "ens")


# --------------------------------------------------------------------------
# estimators
# --------------------------------------------------------------------------

class _TreeParams(Estimator):
    featuresCol = Param("features", "features column", ptype=str)
    labelCol = Param("label", "label column", ptype=str)
    maxDepth = Param(5, "tree depth", ptype=int, validator=lambda v: 1 <= v <= 12)
    maxBins = Param(32, "histogram bins per feature", ptype=int,
                    validator=lambda v: 2 <= v <= 256)
    lam = Param(1.0, "L2 leaf regularization", ptype=float)
    seed = Param(0, "sampling seed", ptype=int)

    def _xy(self, table: DataTable):
        X = _features_matrix(table[self.featuresCol]).astype(np.float32)
        y = np.asarray(table[self.labelCol], np.float64)
        return X, y


def _per_class_forest(X, y, n_classes, *, depth, n_bins, n_trees, lam,
                      subsample, feat_strategy, seed):
    """Probability forests: per class, trees of leaf-mean(indicator)."""
    rng = np.random.default_rng(seed)
    weights, masks = _bagging(len(X), X.shape[1], n_trees, subsample,
                              feat_strategy, rng)
    # one quantile/binning pass shared by all K class ensembles
    edges = quantile_bin_edges(X, n_bins)
    binned = bin_features(jnp.asarray(X, jnp.float32), jnp.asarray(edges))
    ensembles = []
    for c in range(n_classes):
        target = (y == c).astype(np.float32)
        # squared loss from a zero bias: leaf value = smoothed mean of the
        # indicator = P(class | leaf); average over trees with weight 1/T
        ens = _fit_boosted(X, target, depth=depth, n_bins=n_bins,
                           n_trees=n_trees, step=1.0 / n_trees, lam=lam,
                           loss="squared",
                           row_weights=weights if n_trees > 1 else None,
                           feature_masks=masks, boost=False,
                           prebinned=(edges, binned))
        ensembles.append(ens)
    return ensembles


class DecisionTreeClassifier(_TreeParams):
    """Single probability tree (Spark DecisionTreeClassifier counterpart)."""

    def fit(self, table: DataTable) -> TreeClassifierModel:
        X, y = self._xy(table)
        n_classes = int(y.max()) + 1 if len(y) else 2
        ens = _per_class_forest(X, y, max(n_classes, 2), depth=self.maxDepth,
                                n_bins=self.maxBins, n_trees=1, lam=self.lam,
                                subsample=1.0, feat_strategy="all",
                                seed=self.seed)
        return TreeClassifierModel(ens, featuresCol=self.featuresCol)


class RandomForestClassifier(_TreeParams):
    numTrees = Param(20, "trees in the forest", ptype=int)
    subsamplingRate = Param(1.0, "Poisson bootstrap rate", ptype=float)
    featureSubsetStrategy = Param(
        "sqrt", "all | auto | sqrt | log2 | onethird | fraction in (0,1]",
        ptype=str, validator=_valid_strategy)

    def fit(self, table: DataTable) -> TreeClassifierModel:
        X, y = self._xy(table)
        n_classes = int(y.max()) + 1 if len(y) else 2
        ens = _per_class_forest(
            X, y, max(n_classes, 2), depth=self.maxDepth, n_bins=self.maxBins,
            n_trees=self.numTrees, lam=self.lam,
            subsample=self.subsamplingRate,
            feat_strategy=self.featureSubsetStrategy, seed=self.seed)
        return TreeClassifierModel(ens, featuresCol=self.featuresCol)


class GBTClassifier(_TreeParams):
    """Binary logistic boosting; multiclass unsupported, as the reference
    (TrainClassifier.scala:101-104)."""

    maxIter = Param(20, "boosting rounds", ptype=int)
    stepSize = Param(0.1, "shrinkage", ptype=float)

    def fit(self, table: DataTable) -> TreeClassifierModel:
        X, y = self._xy(table)
        if len(y) and y.max() > 1:
            raise ValueError("Multiclass GBTClassifier is not supported "
                             "(reference TrainClassifier.scala:101-104)")
        ens = _fit_boosted(X, y.astype(np.float32), depth=self.maxDepth,
                           n_bins=self.maxBins, n_trees=self.maxIter,
                           step=self.stepSize, lam=self.lam, loss="logistic")
        return TreeClassifierModel([ens], mode="logit",
                                   featuresCol=self.featuresCol)


class DecisionTreeRegressor(_TreeParams):
    def fit(self, table: DataTable) -> TreeRegressorModel:
        X, y = self._xy(table)
        ens = _fit_boosted(X, y.astype(np.float32), depth=self.maxDepth,
                           n_bins=self.maxBins, n_trees=1, step=1.0,
                           lam=self.lam, loss="squared")
        return TreeRegressorModel(ens, featuresCol=self.featuresCol)


class RandomForestRegressor(_TreeParams):
    numTrees = Param(20, "trees in the forest", ptype=int)
    subsamplingRate = Param(1.0, "Poisson bootstrap rate", ptype=float)
    featureSubsetStrategy = Param(
        "sqrt", "all | auto | sqrt | log2 | onethird | fraction in (0,1]",
        ptype=str, validator=_valid_strategy)

    def fit(self, table: DataTable) -> TreeRegressorModel:
        X, y = self._xy(table)
        rng = np.random.default_rng(self.seed)
        weights, masks = _bagging(len(X), X.shape[1], self.numTrees,
                                  self.subsamplingRate,
                                  self.featureSubsetStrategy, rng)
        ens = _fit_boosted(X, y.astype(np.float32), depth=self.maxDepth,
                           n_bins=self.maxBins, n_trees=self.numTrees,
                           step=1.0 / self.numTrees, lam=self.lam,
                           loss="squared", row_weights=weights,
                           feature_masks=masks, boost=False)
        return TreeRegressorModel(ens, featuresCol=self.featuresCol)


class GBTRegressor(_TreeParams):
    maxIter = Param(20, "boosting rounds", ptype=int)
    stepSize = Param(0.1, "shrinkage", ptype=float)

    def fit(self, table: DataTable) -> TreeRegressorModel:
        X, y = self._xy(table)
        ens = _fit_boosted(X, y.astype(np.float32), depth=self.maxDepth,
                           n_bins=self.maxBins, n_trees=self.maxIter,
                           step=self.stepSize, lam=self.lam, loss="squared")
        return TreeRegressorModel(ens, featuresCol=self.featuresCol)
