"""Model selection over one evaluation dataset.

TPU-native counterpart of find-best-model (FindBestModel.scala:68-331):
score each candidate model on the eval table, compare on the chosen metric
with the right direction (higher-is-better for accuracy/precision/recall/
AUC/r2, lower for mse/rmse/mae), and return a BestModel exposing the
winner plus the all-models comparison table and the winner's ROC.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import Estimator, Transformer, load_stage
from mmlspark_tpu.core.schema import SchemaConstants
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.ml.statistics import (ACCURACY, AUC, MAE, METRIC_TO_COLUMN,
                                        MSE, PRECISION, R2, RECALL, RMSE,
                                        ComputeModelStatistics,
                                        _label_indices, _metrics_from_confusion,
                                        _schema_info, confusion_matrix_batch)

_LOWER_IS_BETTER = {MSE, RMSE, MAE}


class FindBestModel(Estimator):
    """Pick the best of several trained models on an eval table."""

    evaluationMetric = Param(ACCURACY, "metric to rank models by", ptype=str,
                             domain=(ACCURACY, PRECISION, RECALL, AUC,
                                     MSE, RMSE, R2, MAE))

    def __init__(self, models: Optional[list[Transformer]] = None, **kw):
        super().__init__(**kw)
        self._models = list(models or [])

    def set_models(self, models: list[Transformer]) -> "FindBestModel":
        self._models = list(models)
        return self

    def fit(self, table: DataTable) -> "BestModel":
        if not self._models:
            raise ValueError("FindBestModel: no models to compare")
        metric = self.evaluationMetric
        col_name = METRIC_TO_COLUMN[metric]
        lower = metric in _LOWER_IS_BETTER

        scored_tables = [model.transform(table) for model in self._models]
        rows = self._batched_rows(scored_tables)
        if rows is None:
            rows = [self._serial_row(model, scored)
                    for model, scored in zip(self._models, scored_tables)]
        best = None
        for i, (model, row) in enumerate(zip(self._models, rows)):
            if col_name not in row:
                raise ValueError(
                    f"metric '{metric}' not produced for model "
                    f"{type(model).__name__} (wrong model kind?)")
            value = float(row[col_name])
            if best is None or (value < best[1] if lower else value > best[1]):
                best = (model, value, i)
        best_model, best_value, best_i = best
        # the winner alone takes the full evaluator pass (its metrics
        # table, confusion matrix, and ROC back the BestModel surface);
        # the non-winners were ranked from the batched confusion matrices
        best_result = ComputeModelStatistics().evaluate(
            scored_tables[best_i])
        # models of different arities emit different metric columns (binary
        # AUC vs multiclass macro_*): take the union, NaN where absent
        all_cols: list[str] = []
        for r in rows:
            for k in r:
                if k not in all_cols:
                    all_cols.append(k)
        table_cols = {c: [r.get(c, np.nan) for r in rows] for c in all_cols}
        return BestModel(best_model, best_result.metrics,
                         DataTable(table_cols),
                         roc=best_result.roc,
                         evaluationMetric=metric)

    def _serial_row(self, model: Transformer, scored: DataTable) -> dict:
        """One full evaluator pass (the pre-batched path, kept for
        regression models and mixed-arity candidate sets)."""
        metrics = ComputeModelStatistics().evaluate(scored).metrics
        return {"model_name": model.uid,
                **{c: float(metrics[c][0]) for c in metrics.columns}}

    def _batched_rows(self, scored_tables: list) -> Optional[list]:
        """Rank every classification candidate from ONE vectorized
        confusion-matrix pass (statistics.confusion_matrix_batch) instead
        of a per-model evaluator round trip — the redundant host work the
        serial loop paid between fits.  Returns None (caller falls back
        to the serial path) when the candidates are not uniformly
        same-arity classifiers: regression metrics and mixed
        binary/multiclass sets keep the per-model evaluator."""
        ys, yps, probs_list, n_cls = [], [], [], set()
        for scored in scored_tables:
            try:
                kind, label, scores, scored_labels, probs = _schema_info(
                    scored, None)
            except ValueError:
                return None
            if kind != SchemaConstants.CLASSIFICATION_KIND:
                return None
            pred_col = scored_labels or scores
            try:
                y = _label_indices(scored, label, pred_col)
            except ValueError:
                return None
            yp = np.asarray(scored[pred_col], np.float64).astype(np.int64)
            levels = scored.meta(pred_col).categorical
            n_cls.add(max(levels.num_levels if levels is not None else 0,
                          int(max(y.max(initial=0), yp.max(initial=0))) + 1,
                          2))
            ys.append(y)
            yps.append(yp)
            probs_list.append(
                np.asarray(scored[probs], np.float64)
                if probs is not None and probs in scored else None)
        if len(n_cls) != 1 or len({len(y) for y in ys}) != 1:
            return None  # mixed arities / row counts: evaluate per model
        k = n_cls.pop()
        cms = confusion_matrix_batch(np.stack(ys), np.stack(yps),
                                     n_classes=k)
        rows = []
        for model, cm, y, p in zip(self._models, cms, ys, probs_list):
            out, _ = _metrics_from_confusion(cm, y, p)
            rows.append({"model_name": model.uid, **out})
        return rows


class BestModel(Transformer):
    """The chosen model + comparison tables (FindBestModel.scala:174-227)."""

    evaluationMetric = Param(ACCURACY, "metric models were ranked by", ptype=str)

    def __init__(self, best_model: Optional[Transformer] = None,
                 best_metrics: Optional[DataTable] = None,
                 all_model_metrics: Optional[DataTable] = None,
                 roc: Optional[tuple] = None, **kw):
        super().__init__(**kw)
        self._best = best_model
        self._best_metrics = best_metrics
        self._all_metrics = all_model_metrics
        self._roc = roc

    @property
    def best_model(self) -> Transformer:
        return self._best

    def get_evaluation_results(self) -> DataTable:
        return self._best_metrics

    def get_all_model_metrics(self) -> DataTable:
        return self._all_metrics

    def get_roc_curve(self) -> DataTable:
        if self._roc is None:
            raise ValueError("best model produced no binary ROC")
        from mmlspark_tpu.ml.statistics import roc_table
        return roc_table(self._roc)

    def transform(self, table: DataTable) -> DataTable:
        return self._best.transform(table)

    def _save_extra(self, path: str) -> None:
        self._best.save(os.path.join(path, "best"))
        if self._best_metrics is not None:
            self._best_metrics.save(os.path.join(path, "best_metrics"))
        if self._all_metrics is not None:
            self._all_metrics.save(os.path.join(path, "all_metrics"))
        if self._roc is not None:
            np.savez(os.path.join(path, "roc.npz"),
                     fpr=np.asarray(self._roc[0]), tpr=np.asarray(self._roc[1]),
                     thresholds=np.asarray(self._roc[2]))

    def _load_extra(self, path: str) -> None:
        self._best = load_stage(os.path.join(path, "best"))
        bm = os.path.join(path, "best_metrics")
        am = os.path.join(path, "all_metrics")
        self._best_metrics = DataTable.load(bm) if os.path.exists(bm) else None
        self._all_metrics = DataTable.load(am) if os.path.exists(am) else None
        roc_path = os.path.join(path, "roc.npz")
        if os.path.exists(roc_path):
            z = np.load(roc_path)
            self._roc = (z["fpr"], z["tpr"], z["thresholds"])
        else:
            self._roc = None
