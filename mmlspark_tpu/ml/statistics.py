"""Model evaluation: aggregate and per-instance statistics.

TPU-native counterpart of compute-model-statistics and
compute-per-instance-statistics (ComputeModelStatistics.scala:104-530,
ComputePerInstanceStatistics.scala:36-92).  Scored columns are discovered
through the `mml` metadata protocol (core/schema.py), never by hard-coded
names — the same contract the reference relies on
(ComputeModelStatistics.scala:205-218).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import Evaluator
from mmlspark_tpu.core.schema import (SchemaConstants, find_score_columns,
                                      set_score_column)
from mmlspark_tpu.core.table import DataTable

# metric names (ComputeModelStatistics.scala:26-69)
MSE, RMSE, R2, MAE = "mse", "rmse", "r2", "mae"
AUC, ACCURACY, PRECISION, RECALL = "AUC", "accuracy", "precision", "recall"
ALL_METRICS = "all"
MSE_COL = "mean_squared_error"
RMSE_COL = "root_mean_squared_error"
R2_COL = "R^2"
MAE_COL = "mean_absolute_error"
AVG_ACCURACY = "average_accuracy"
MACRO_RECALL = "macro_averaged_recall"
MACRO_PRECISION = "macro_averaged_precision"

METRIC_TO_COLUMN = {MSE: MSE_COL, RMSE: RMSE_COL, R2: R2_COL, MAE: MAE_COL,
                    AUC: AUC, ACCURACY: ACCURACY, PRECISION: PRECISION,
                    RECALL: RECALL}
CLASSIFICATION_METRICS = {ACCURACY, PRECISION, RECALL, AUC}
REGRESSION_METRICS = {MSE, RMSE, R2, MAE}


def _schema_info(table: DataTable, label_fallback: Optional[str]):
    """Resolve (model_kind, label_col, scores_col, scored_labels_col,
    probabilities_col) from metadata (getSchemaInfo, scala:205-218)."""
    cols = find_score_columns(table)
    if not cols:
        raise ValueError(
            "no scored columns found in table metadata; score the table "
            "with a trained model first")
    C = SchemaConstants
    any_col = next(iter(cols.values()))
    kind = table.meta(any_col).model_kind
    label = cols.get(C.TRUE_LABELS_COLUMN) or label_fallback
    if label is None or label not in table:
        raise ValueError("no true-label column found (metadata or labelCol)")
    return (kind, label, cols.get(C.SCORES_COLUMN),
            cols.get(C.SCORED_LABELS_COLUMN),
            cols.get(C.SCORED_PROBABILITIES_COLUMN))


def _label_indices(table: DataTable, label: str,
                   pred_col: Optional[str]) -> np.ndarray:
    """True labels as class indices.

    At score time the label column may still hold raw values (strings);
    they are mapped through the scored-labels categorical levels carried by
    the trained model (TrainClassifier.scala:253-263), the same resolution
    the reference evaluator performs via metadata.
    """
    arr = table[label]
    own = table.meta(label).categorical
    levels = (table.meta(pred_col).categorical
              if pred_col is not None and pred_col in table else None)
    if own is not None:
        # the label's own encoding is authoritative only if it matches the
        # model's fitted levels; otherwise decode + re-map (same rule as
        # feature columns, assemble.py _categorical_indices)
        if levels is None or list(own.levels) == list(levels.levels):
            return np.asarray(arr, np.int64)
        values = list(own.to_levels(np.asarray(arr, np.int64)))
        idx = levels.to_indices(values).astype(np.int64)
    elif arr.dtype == object or np.issubdtype(arr.dtype, np.str_):
        if levels is None:
            raise ValueError(
                f"label column '{label}' is non-numeric and no levels are "
                "available on the scored labels")
        idx = levels.to_indices(list(arr)).astype(np.int64)
    else:
        vals = np.asarray(arr, np.float64)
        if levels is None:
            return vals.astype(np.int64)
        # raw numeric values: predictions live in fitted-level index space,
        # so map raw values through the levels whenever they match them;
        # only treat values as indices if they can't be raw level values
        uniq = set(np.unique(vals).tolist())
        if uniq <= set(_as_plain(levels.levels)):
            idx = levels.to_indices(vals.tolist()).astype(np.int64)
        elif uniq <= set(range(levels.num_levels)):
            return vals.astype(np.int64)
        else:
            idx = np.full(len(vals), -1, np.int64)
    if (idx < 0).any():
        unseen = sorted({str(v) for v, i in zip(arr, idx) if i < 0})[:5]
        raise ValueError(
            f"label column '{label}' contains values never seen at train "
            f"time: {unseen}; metrics would be silently wrong")
    return idx


def _as_plain(levels) -> list:
    return [float(v) if isinstance(v, (int, float)) and not isinstance(v, bool)
            else v for v in levels]


def confusion_matrix(y_true: np.ndarray, y_pred: np.ndarray,
                     n_classes: Optional[int] = None) -> np.ndarray:
    """Row = true class, column = predicted (scala:461-484)."""
    yt = np.asarray(y_true, np.int64)
    yp = np.asarray(y_pred, np.int64)
    k = n_classes or int(max(yt.max(initial=0), yp.max(initial=0))) + 1
    cm = np.zeros((k, k), np.int64)
    np.add.at(cm, (yt, yp), 1)
    return cm


def roc_curve(y_true: np.ndarray, scores: np.ndarray):
    """(fpr, tpr, thresholds), sweeping the decision threshold."""
    y = np.asarray(y_true, np.float64)
    s = np.asarray(scores, np.float64)
    order = np.argsort(-s, kind="stable")
    y, s = y[order], s[order]
    distinct = np.where(np.diff(s))[0]
    idx = np.concatenate([distinct, [len(y) - 1]])
    tps = np.cumsum(y)[idx]
    fps = (idx + 1) - tps
    P = max(y.sum(), 1e-12)
    N = max(len(y) - y.sum(), 1e-12)
    tpr = np.concatenate([[0.0], tps / P])
    fpr = np.concatenate([[0.0], fps / N])
    thresholds = np.concatenate([[np.inf], s[idx]])
    return fpr, tpr, thresholds


def auc_score(y_true: np.ndarray, scores: np.ndarray) -> float:
    fpr, tpr, _ = roc_curve(y_true, scores)
    return float(np.trapezoid(tpr, fpr))


@dataclasses.dataclass
class EvalResult:
    """One evaluation, returned as a value (the data the reference logged
    through MetricData, scala:486-521) — the evaluator itself stays
    stateless, so concurrent/repeated use is safe."""

    metrics: DataTable
    confusion_matrix: Optional[np.ndarray] = None
    roc: Optional[tuple] = None  # (fpr, tpr, thresholds)

    def confusion_matrix_table(self) -> DataTable:
        if self.confusion_matrix is None:
            raise ValueError("no confusion matrix (regression evaluation?)")
        cm = self.confusion_matrix
        return DataTable({f"pred_{j}": cm[:, j] for j in range(cm.shape[1])})

    def roc_curve_table(self) -> DataTable:
        if self.roc is None:
            raise ValueError("no binary ROC computed")
        return roc_table(self.roc)

    def to_metric_data(self, metric_type: str = "evaluation",
                       model_name: str = "model"):
        """The typed logging contract (reference Metrics.scala:37-47; the
        scala logs scalar metrics AND the full ROC table through it,
        ComputeModelStatistics.scala:486-521)."""
        from mmlspark_tpu.observe import MetricData
        return MetricData.create(
            {k: float(self.metrics[k][0]) for k in self.metrics.columns},
            metric_type, model_name)

    def roc_metric_data(self, model_name: str = "model"):
        if self.roc is None:
            raise ValueError("no binary ROC computed")
        fpr, tpr, thr = self.roc
        from mmlspark_tpu.observe import MetricData
        return MetricData.create_table(
            {"false_positive_rate": list(fpr), "true_positive_rate": list(tpr),
             "threshold": list(np.clip(thr, -1e300, 1e300))},
            "roc", model_name)


def roc_table(roc: tuple) -> DataTable:
    fpr, tpr, thr = roc
    return DataTable({"false_positive_rate": np.asarray(fpr),
                      "true_positive_rate": np.asarray(tpr),
                      "threshold": np.asarray(thr)})


class ComputeModelStatistics(Evaluator):
    """Emit a one-row metrics table for a scored table.

    `evaluate` returns the full `EvalResult` (metrics + confusion matrix +
    ROC); `transform` is the pipeline face and returns just the metrics
    table.  Both are stateless.
    """

    evaluationMetric = Param(ALL_METRICS, "metric to compute ('all' or one "
                             "of accuracy/precision/recall/AUC/mse/rmse/r2/mae)",
                             ptype=str)
    labelCol = Param(None, "fallback true-label column when metadata has none",
                     ptype=str)

    def evaluate(self, table: DataTable) -> EvalResult:
        kind, label, scores, scored_labels, probs = _schema_info(
            table, self.labelCol)
        metric = self.evaluationMetric
        if kind == SchemaConstants.REGRESSION_KIND:
            result = self._regression(table, label, scores, metric)
        else:
            result = self._classification(table, label, scores,
                                          scored_labels, probs, metric)
        # every evaluation flows through the typed metric contract
        # (reference ComputeModelStatistics.scala:486-521 -> MetricData)
        result.to_metric_data(metric_type=kind).log("ml.statistics", "debug")
        return result

    def transform(self, table: DataTable) -> DataTable:
        return self.evaluate(table).metrics

    # -- regression (scala:186-203) --------------------------------------
    def _regression(self, table, label, scores, metric) -> EvalResult:
        y = np.asarray(table[label], np.float64)
        pred = np.asarray(table[scores], np.float64)
        err = y - pred
        mse = float(np.mean(err ** 2))
        out = {MSE_COL: mse, RMSE_COL: float(np.sqrt(mse)),
               R2_COL: float(1.0 - mse / max(np.var(y), 1e-24)),
               MAE_COL: float(np.mean(np.abs(err)))}
        if metric in REGRESSION_METRICS:
            out = {METRIC_TO_COLUMN[metric]: out[METRIC_TO_COLUMN[metric]]}
        return EvalResult(DataTable({k: [v] for k, v in out.items()}))

    # -- classification (scala:143-185, 375-447) -------------------------
    def _classification(self, table, label, scores, scored_labels, probs,
                        metric) -> EvalResult:
        pred_col = scored_labels or scores
        y = _label_indices(table, label, pred_col)
        yp = np.asarray(table[pred_col], np.float64).astype(np.int64)
        levels = table.meta(pred_col).categorical
        n_classes = max(
            levels.num_levels if levels is not None else 0,
            int(max(y.max(initial=0), yp.max(initial=0))) + 1, 2)
        cm = confusion_matrix(y, yp, n_classes)
        p = np.asarray(table[probs], np.float64) if probs is not None \
            else None
        out, roc = _metrics_from_confusion(cm, y, p)
        if n_classes != 2 and metric == AUC:
            raise ValueError("AUC is not available for multiclass "
                             "(scala:173)")
        if metric in CLASSIFICATION_METRICS and metric in out:
            out = {metric: out[metric]}
        return EvalResult(DataTable({k: [v] for k, v in out.items()}),
                          confusion_matrix=cm, roc=roc)


def _metrics_from_confusion(cm: np.ndarray, y: Optional[np.ndarray] = None,
                            probs: Optional[np.ndarray] = None
                            ) -> tuple[dict, Optional[tuple]]:
    """The classification metric arithmetic on ONE confusion matrix
    (binary: accuracy/precision/recall + AUC when probabilities are
    given; multiclass: micro + the macro family, scala:375-429).  Shared
    by the serial evaluator and `classification_report_batch`, so the
    batched sweep path agrees with per-model evaluation by construction."""
    out: dict[str, float] = {}
    roc = None
    if cm.shape[0] == 2:
        tn, fp, fn, tp = cm[0, 0], cm[0, 1], cm[1, 0], cm[1, 1]
        total = cm.sum()
        out[ACCURACY] = float((tp + tn) / max(total, 1))
        out[PRECISION] = float(tp / max(tp + fp, 1))
        out[RECALL] = float(tp / max(tp + fn, 1))
        if probs is not None and y is not None:
            pos = probs[:, 1] if probs.ndim == 2 else probs
            roc = roc_curve(y, pos)
            fpr, tpr, _ = roc
            out[AUC] = float(np.trapezoid(tpr, fpr))
    else:
        # micro-averaged accuracy == overall accuracy; macro averages
        # per-class (scala:375-429)
        diag = np.diag(cm).astype(np.float64)
        row = cm.sum(axis=1).astype(np.float64)  # per true class
        col = cm.sum(axis=0).astype(np.float64)  # per predicted class
        micro = float(diag.sum() / max(cm.sum(), 1))
        out[ACCURACY] = micro
        out[PRECISION] = micro   # micro precision == micro recall == acc
        out[RECALL] = micro
        out[AVG_ACCURACY] = float(np.mean(
            (cm.sum() - row - col + 2 * diag) / max(cm.sum(), 1)))
        out[MACRO_PRECISION] = float(np.mean(diag / np.maximum(col, 1)))
        out[MACRO_RECALL] = float(np.mean(diag / np.maximum(row, 1)))
    return out, roc


def confusion_matrix_batch(y_true_stack: np.ndarray,
                           y_pred_stack: np.ndarray,
                           n_classes: Optional[int] = None) -> np.ndarray:
    """(M, k, k) confusion matrices for M models in ONE scatter-add pass
    — the host-side cost of evaluating a whole sweep population is one
    vectorized histogram instead of M table round trips."""
    yt = np.asarray(y_true_stack, np.int64)
    yp = np.asarray(y_pred_stack, np.int64)
    if yp.ndim != 2:
        raise ValueError(f"predictions must be stacked (M, rows); got "
                         f"shape {yp.shape}")
    m, n = yp.shape
    if yt.ndim == 1:
        yt = np.broadcast_to(yt, (m, n))
    k = n_classes or int(max(yt.max(initial=0), yp.max(initial=0))) + 1
    k = max(k, 2)
    cms = np.zeros((m, k, k), np.int64)
    mi = np.broadcast_to(np.arange(m)[:, None], (m, n))
    np.add.at(cms, (mi, yt, yp), 1)
    return cms


def classification_report_batch(y_true, y_pred_stack,
                                model_uids: Optional[list] = None,
                                probs_stack: Optional[np.ndarray] = None,
                                n_classes: Optional[int] = None) -> DataTable:
    """Evaluate M models' stacked predictions in one batched pass.

    `y_pred_stack` is (M, rows) predicted class indices — e.g. a
    population sweep's `score_population` argmax — and `y_true` is
    shared (rows,) or per-model (M, rows).  Returns a DataTable with one
    row per model (`model_name` + the same metric columns the serial
    evaluator emits, union over binary/multiclass arities).  The metric
    arithmetic is `_metrics_from_confusion`, shared with
    `ComputeModelStatistics`, so values match per-model evaluation
    exactly while the confusion matrices come from a single vectorized
    scatter-add instead of M mml-tagged table round trips
    (FindBestModel's candidate ranking; TrainClassifier's sweep path).
    """
    yp = np.asarray(y_pred_stack, np.int64)
    cms = confusion_matrix_batch(y_true, yp, n_classes)
    m = yp.shape[0]
    yt = np.asarray(y_true, np.int64)
    uids = list(model_uids) if model_uids is not None \
        else [f"model_{i}" for i in range(m)]
    if len(uids) != m:
        raise ValueError(f"{len(uids)} model uids for {m} models")
    rows = []
    for i in range(m):
        y_i = yt[i] if yt.ndim == 2 else yt
        p_i = probs_stack[i] if probs_stack is not None else None
        out, _ = _metrics_from_confusion(cms[i], y_i, p_i)
        rows.append({"model_name": uids[i], **out})
    cols: list[str] = []
    for r in rows:
        for key in r:
            if key not in cols:
                cols.append(key)
    return DataTable({c: [r.get(c, np.nan) for r in rows] for c in cols})


def classification_report(y_true, y_pred, model_uid: str = "model") -> EvalResult:
    """Evaluate raw predicted class indices against true labels through the
    full metadata-driven evaluator: builds the one-model mml-tagged table
    the protocol expects and runs ComputeModelStatistics on it.

    The building block of the quantization accuracy gate
    (quant/gate.py::accuracy_gate): quantized-vs-f32 comparisons go through
    the SAME metric path as every other evaluation in the framework, so
    an accuracy delta in a bench line and one from a notebook agree by
    construction.
    """
    t = DataTable({"label": np.asarray(y_true),
                   "prediction": np.asarray(y_pred)})
    set_score_column(t, model_uid, "prediction",
                     SchemaConstants.SCORED_LABELS_COLUMN,
                     SchemaConstants.CLASSIFICATION_KIND)
    set_score_column(t, model_uid, "label",
                     SchemaConstants.TRUE_LABELS_COLUMN,
                     SchemaConstants.CLASSIFICATION_KIND)
    return ComputeModelStatistics(evaluationMetric=ACCURACY).evaluate(t)


class ComputePerInstanceStatistics(Evaluator):
    """Per-row metrics: log-loss for classification, L1/L2 loss for
    regression (ComputePerInstanceStatistics.scala:36-92)."""

    labelCol = Param(None, "fallback true-label column", ptype=str)

    def transform(self, table: DataTable) -> DataTable:
        kind, label, scores, scored_labels, probs = _schema_info(
            table, self.labelCol)
        if kind == SchemaConstants.REGRESSION_KIND:
            y = np.asarray(table[label], np.float64)
            pred = np.asarray(table[scores], np.float64)
            out = table.with_column("L1_loss", np.abs(y - pred))
            return out.with_column("L2_loss", (y - pred) ** 2)
        if probs is None:
            raise ValueError("classification per-instance stats need a "
                             "scored-probabilities column")
        y = _label_indices(table, label, scored_labels)
        p = np.asarray(table[probs], np.float64)
        idx = np.clip(y, 0, p.shape[1] - 1)
        true_p = p[np.arange(len(y)), idx]
        log_loss = -np.log(np.maximum(true_p, 1e-15))
        return table.with_column("log_loss", log_loss)
