"""AutoML layer (reference L4: train-classifier, train-regressor,
compute-model-statistics, compute-per-instance-statistics, find-best-model)."""

from mmlspark_tpu.ml.learners import (
    LinearRegression,
    LogisticRegression,
    MultilayerPerceptronClassifier,
    NaiveBayes,
    OneVsRest,
)
from mmlspark_tpu.ml.trees import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
    GBTClassifier,
    GBTRegressor,
    RandomForestClassifier,
    RandomForestRegressor,
)
from mmlspark_tpu.ml.train_classifier import TrainClassifier, TrainedClassifierModel
from mmlspark_tpu.ml.train_regressor import TrainRegressor, TrainedRegressorModel
from mmlspark_tpu.ml.statistics import (
    ComputeModelStatistics,
    ComputePerInstanceStatistics,
    EvalResult,
)
from mmlspark_tpu.ml.find_best_model import BestModel, FindBestModel

__all__ = [
    "LogisticRegression", "LinearRegression", "NaiveBayes",
    "MultilayerPerceptronClassifier", "OneVsRest",
    "DecisionTreeClassifier", "RandomForestClassifier", "GBTClassifier",
    "DecisionTreeRegressor", "RandomForestRegressor", "GBTRegressor",
    "TrainClassifier", "TrainedClassifierModel",
    "TrainRegressor", "TrainedRegressorModel",
    "ComputeModelStatistics", "ComputePerInstanceStatistics", "EvalResult",
    "FindBestModel", "BestModel",
]
