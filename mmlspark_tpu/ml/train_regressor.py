"""TrainRegressor: auto-ML regression estimator.

TPU-native counterpart of the reference's train-regressor
(TrainRegressor.scala:43-117): cast the label to double, drop rows with
missing labels, featurize the remaining columns (same per-learner settings
as TrainClassifier), fit, and tag scored columns as regression outputs.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import (Estimator, PipelineModel, Transformer,
                                        load_stage)
from mmlspark_tpu.core.schema import SchemaConstants, set_score_column
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.feature.assemble import (NUM_FEATURES_DEFAULT,
                                           NUM_FEATURES_TREE_OR_NN, Featurize)
from mmlspark_tpu.ml.learners import LinearRegression
from mmlspark_tpu.ml.train_classifier import _is_tree


class TrainRegressor(Estimator):
    labelCol = Param("label", "target column", ptype=str)
    featuresCol = Param("features", "assembled features column", ptype=str)
    numFeatures = Param(0, "hash space size (0 = per-learner default)",
                        ptype=int)

    def __init__(self, model: Optional[Estimator] = None, **kw):
        super().__init__(**kw)
        self._model = model

    def set_model(self, model: Estimator) -> "TrainRegressor":
        self._model = model
        return self

    def fit(self, table: DataTable) -> "TrainedRegressorModel":
        learner = self._model if self._model is not None else LinearRegression()
        label = self.labelCol
        data = table.drop_nulls([label])
        # label -> double (TrainRegressor.scala:77-95)
        data = data.with_column(label, np.asarray(data[label], np.float64))

        is_tree = _is_tree(learner)
        num_features = self.numFeatures or (
            NUM_FEATURES_TREE_OR_NN if is_tree else NUM_FEATURES_DEFAULT)
        feature_cols = [c for c in data.columns if c != label]
        featurizer = Featurize(
            featureColumns={self.featuresCol: feature_cols},
            numberOfFeatures=num_features,
            oneHotEncodeCategoricals=not is_tree)
        featurized_model = featurizer.fit(data)
        processed = featurized_model.transform(data)

        learner.set_params(featuresCol=self.featuresCol, labelCol=label)
        fit_model = learner.fit(processed)
        pipeline = PipelineModel([featurized_model, fit_model])
        return TrainedRegressorModel(pipeline, labelCol=label,
                                     featuresCol=self.featuresCol)

    def _save_extra(self, path: str) -> None:
        if self._model is not None:
            self._model.save(os.path.join(path, "model"))

    def _load_extra(self, path: str) -> None:
        p = os.path.join(path, "model")
        self._model = load_stage(p) if os.path.exists(p) else None


class TrainedRegressorModel(Transformer):
    labelCol = Param("label", "target column", ptype=str)
    featuresCol = Param("features", "features column", ptype=str)

    def __init__(self, pipeline: Optional[PipelineModel] = None, **kw):
        super().__init__(**kw)
        self._pipeline = pipeline

    @property
    def fit_model(self):
        return self._pipeline.get_stages()[-1] if self._pipeline else None

    def transform(self, table: DataTable) -> DataTable:
        out = self._pipeline.transform(table)
        C = SchemaConstants
        if "prediction" in out:
            out = out.rename({"prediction": C.SCORES_COLUMN})
        if C.SCORES_COLUMN in out:
            set_score_column(out, self.uid, C.SCORES_COLUMN, C.SCORES_COLUMN,
                             C.REGRESSION_KIND)
        if self.labelCol in out:
            set_score_column(out, self.uid, self.labelCol,
                             C.TRUE_LABELS_COLUMN, C.REGRESSION_KIND)
        return out

    def _save_extra(self, path: str) -> None:
        self._pipeline.save(os.path.join(path, "pipeline"))

    def _load_extra(self, path: str) -> None:
        self._pipeline = load_stage(os.path.join(path, "pipeline"))
