"""TrainClassifier: auto-ML classification estimator.

TPU-native counterpart of the reference's train-classifier
(TrainClassifier.scala:49-160): index the label to categorical (keeping the
levels), pick featurization settings per learner family (hash-space size,
one-hot on/off), featurize every non-label column, autosize the MLP input
layer, fit the learner, and return a model whose transform tags the scored
columns in metadata (lines 213-264) so evaluators find them without
hard-coded names.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import (Estimator, PipelineModel, Transformer,
                                        load_stage)
from mmlspark_tpu.core.schema import (CategoricalMap, SchemaConstants,
                                      make_categorical, set_score_column)
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.feature.assemble import (NUM_FEATURES_DEFAULT,
                                           NUM_FEATURES_TREE_OR_NN, Featurize)
from mmlspark_tpu.ml.learners import (LogisticRegression,
                                      MultilayerPerceptronClassifier,
                                      OneVsRest)

_TREE_LEARNERS = ("DecisionTreeClassifier", "RandomForestClassifier",
                  "GBTClassifier", "DecisionTreeRegressor",
                  "RandomForestRegressor", "GBTRegressor")


def _is_tree(est) -> bool:
    return type(est).__name__ in _TREE_LEARNERS


class TrainClassifier(Estimator):
    """Featurize + fit a classifier with label indexing."""

    labelCol = Param("label", "label column", ptype=str)
    featuresCol = Param("features", "assembled features column", ptype=str)
    numFeatures = Param(0, "hash space size (0 = per-learner default, "
                        "Featurize.scala:13-19)", ptype=int)
    indexLabel = Param(True, "convert label to categorical indices", ptype=bool)
    populationSize = Param(0, "when > 1 and the learner is the MLP, train a "
                           "population of candidates at log-spaced learning "
                           "rates around stepSize in ONE vmapped program "
                           "(train/sweep.py) and keep the winner", ptype=int)
    sweepLearningRates = Param(None, "explicit learning-rate grid for the "
                               "population sweep (one member per rate; "
                               "overrides populationSize)",
                               ptype=(list, tuple))
    sweepHalvingRungs = Param(0, "successive-halving rungs for the sweep "
                              "(0 = train every member to completion)",
                              ptype=int)

    def __init__(self, model: Optional[Estimator] = None, **kw):
        super().__init__(**kw)
        self._model = model

    def set_model(self, model: Estimator) -> "TrainClassifier":
        self._model = model
        return self

    def fit(self, table: DataTable) -> "TrainedClassifierModel":
        learner = self._model if self._model is not None else LogisticRegression()
        label = self.labelCol
        data = table.drop_nulls([label])

        levels: Optional[list] = None
        if self.indexLabel:
            if not data.meta(label).is_categorical:
                data = make_categorical(data, label)
            cmap = data.meta(label).categorical
            levels = list(cmap.levels)

        # per-learner featurization config (TrainClassifier.scala:74-86)
        is_tree = _is_tree(learner)
        is_mlp = isinstance(learner, MultilayerPerceptronClassifier)
        one_hot = not is_tree
        num_features = self.numFeatures or (
            NUM_FEATURES_TREE_OR_NN if (is_tree or is_mlp)
            else NUM_FEATURES_DEFAULT)

        # class count: from the levels, or from the raw integer labels when
        # indexLabel is off
        if levels is not None:
            n_classes = len(levels)
        else:
            y = np.asarray(data[label], np.float64)
            n_classes = int(y.max(initial=0)) + 1 if len(y) else 2

        # multiclass LR -> one-vs-rest (TrainClassifier.scala:87-95)
        if isinstance(learner, LogisticRegression) and n_classes > 2:
            learner = OneVsRest(learner)

        feature_cols = [c for c in data.columns if c != label]
        featurizer = Featurize(
            featureColumns={self.featuresCol: feature_cols},
            numberOfFeatures=num_features,
            oneHotEncodeCategoricals=one_hot)
        featurized_model = featurizer.fit(data)
        processed = featurized_model.transform(data)

        # MLP input autosizing (TrainClassifier.scala:143-150)
        if is_mlp:
            dim = processed[self.featuresCol].shape[1]
            layers = list(learner.layers or [-1, 100, -1])
            layers[0] = dim
            if layers[-1] in (-1, 0, None):
                layers[-1] = max(n_classes, 2)
            learner = learner.copy(layers=layers)

        learner.set_params(featuresCol=self.featuresCol, labelCol=label)
        sweep_metrics = None
        rates = self._sweep_rates(learner) if is_mlp else None
        if rates:
            # the population path: featurized ONCE above, then every
            # candidate trains inside one vmapped program and the winner
            # is picked by one batched evaluation (train/sweep.py)
            fit_model, sweep_metrics = learner.fit_population(
                processed, rates, int(self.sweepHalvingRungs))
        else:
            fit_model = learner.fit(processed)
        pipeline = PipelineModel([featurized_model, fit_model])
        model = TrainedClassifierModel(
            pipeline, levels=levels, labelCol=label,
            featuresCol=self.featuresCol)
        model._sweep_metrics = sweep_metrics
        return model

    def _sweep_rates(self, learner) -> Optional[list]:
        """The candidate learning-rate grid, or None for a plain fit:
        an explicit sweepLearningRates list wins; populationSize > 1
        log-spaces a decade either side of the learner's stepSize."""
        if self.sweepLearningRates:
            return [float(r) for r in self.sweepLearningRates]
        n = int(self.populationSize)
        if n <= 1:
            return None
        base = float(learner.stepSize)
        return [float(r) for r in np.geomspace(base / 10.0, base * 10.0, n)]

    def _save_extra(self, path: str) -> None:
        if self._model is not None:
            self._model.save(os.path.join(path, "model"))

    def _load_extra(self, path: str) -> None:
        p = os.path.join(path, "model")
        self._model = load_stage(p) if os.path.exists(p) else None


class TrainedClassifierModel(Transformer):
    """Scores a table and tags scored columns in metadata
    (TrainClassifier.scala:213-264)."""

    labelCol = Param("label", "label column", ptype=str)
    featuresCol = Param("features", "features column", ptype=str)

    def __init__(self, pipeline: Optional[PipelineModel] = None,
                 levels: Optional[list] = None, **kw):
        super().__init__(**kw)
        self._pipeline = pipeline
        self._levels = list(levels) if levels is not None else None
        self._sweep_metrics: Optional[DataTable] = None

    @property
    def levels(self) -> Optional[list]:
        return self._levels

    @property
    def sweep_metrics(self) -> Optional[DataTable]:
        """Per-member metrics of the population sweep that produced this
        model (one row per candidate learning rate), or None for a plain
        fit."""
        return self._sweep_metrics

    @property
    def featurized_model(self):
        return self._pipeline.get_stages()[0] if self._pipeline else None

    @property
    def fit_model(self):
        return self._pipeline.get_stages()[-1] if self._pipeline else None

    def transform(self, table: DataTable) -> DataTable:
        out = self._pipeline.transform(table)
        C = SchemaConstants
        renames = {"rawPrediction": C.SCORES_COLUMN,
                   "probability": C.SCORED_PROBABILITIES_COLUMN,
                   "prediction": C.SCORED_LABELS_COLUMN}
        out = out.rename({k: v for k, v in renames.items() if k in out})
        for kind, col in ((C.SCORES_COLUMN, C.SCORES_COLUMN),
                          (C.SCORED_PROBABILITIES_COLUMN,
                           C.SCORED_PROBABILITIES_COLUMN),
                          (C.SCORED_LABELS_COLUMN, C.SCORED_LABELS_COLUMN)):
            if col in out:
                set_score_column(out, self.uid, col, kind,
                                 C.CLASSIFICATION_KIND)
        if self.labelCol in out:
            set_score_column(out, self.uid, self.labelCol,
                             C.TRUE_LABELS_COLUMN, C.CLASSIFICATION_KIND)
        # carry the label levels on the scored labels (scala:253-263)
        if self._levels is not None and C.SCORED_LABELS_COLUMN in out:
            meta = out.meta(C.SCORED_LABELS_COLUMN)
            meta.categorical = CategoricalMap(list(self._levels))
            out.set_meta(C.SCORED_LABELS_COLUMN, meta)
        return out

    # -- persistence ----------------------------------------------------
    def _save_extra(self, path: str) -> None:
        self._pipeline.save(os.path.join(path, "pipeline"))
        with open(os.path.join(path, "levels.json"), "w") as f:
            json.dump(self._levels, f)
        if self._sweep_metrics is not None:
            self._sweep_metrics.save(os.path.join(path, "sweep_metrics"))

    def _load_extra(self, path: str) -> None:
        self._pipeline = load_stage(os.path.join(path, "pipeline"))
        with open(os.path.join(path, "levels.json")) as f:
            self._levels = json.load(f)
        sm = os.path.join(path, "sweep_metrics")
        self._sweep_metrics = DataTable.load(sm) if os.path.exists(sm) \
            else None
