"""JAX learners with SparkML-shaped contracts.

The reference trains through Spark MLlib estimators (LogisticRegression,
MultilayerPerceptronClassifier, NaiveBayes, linear/tree regressors —
dispatched in TrainClassifier.scala:74-129).  Here each learner is a
jit-compiled array program: full-batch L-BFGS for the convex models (one
XLA while_loop, matmul-dominated — MXU-friendly), the flax/optax Trainer
for the MLP, and closed-form solves for linear regression.

Output-column contract matches SparkML so TrainClassifier/Regressor can
rename+tag uniformly: `rawPrediction` (margins/logits), `probability`,
`prediction` for classifiers; `prediction` for regressors.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from mmlspark_tpu.core.params import Param, ParamError
from mmlspark_tpu.core.pipeline import Estimator, Transformer, load_stage
from mmlspark_tpu.core.table import DataTable


def _features_matrix(col: np.ndarray) -> np.ndarray:
    if col.dtype == object:
        return (np.stack([np.asarray(v, np.float32).ravel() for v in col])
                if len(col) else np.zeros((0, 1), np.float32))
    arr = col.astype(np.float32)
    return arr[:, None] if arr.ndim == 1 else arr.reshape(len(arr), -1)


# --------------------------------------------------------------------------
# L-BFGS driver (the standard optax while_loop pattern), jitted once per
# objective shape.
# --------------------------------------------------------------------------

def run_lbfgs(loss_fn, init_params, max_iter: int, tol: float):
    opt = optax.lbfgs()
    value_and_grad = optax.value_and_grad_from_state(loss_fn)

    def step(carry):
        params, state = carry
        value, grad = value_and_grad(params, state=state)
        updates, state = opt.update(grad, state, params, value=value,
                                    grad=grad, value_fn=loss_fn)
        params = optax.apply_updates(params, updates)
        return params, state

    def cont(carry):
        _, state = carry
        count = optax.tree_utils.tree_get(state, "count")
        grad = optax.tree_utils.tree_get(state, "grad")
        # tree_norm arrived in optax 0.2.4; tree_l2_norm is the older name
        norm_fn = getattr(optax.tree_utils, "tree_norm",
                          optax.tree_utils.tree_l2_norm)
        err = norm_fn(grad)
        return (count == 0) | ((count < max_iter) & (err >= tol))

    final_params, _ = jax.lax.while_loop(cont, step,
                                         (init_params, opt.init(init_params)))
    return final_params


@jax.jit
def _sigmoid(z):
    return jax.nn.sigmoid(z)


# --------------------------------------------------------------------------
# Classifier model base: transform() contract
# --------------------------------------------------------------------------

class ClassifierModel(Transformer):
    """Adds rawPrediction / probability / prediction columns."""

    featuresCol = Param("features", "features column", ptype=str)
    rawPredictionCol = Param("rawPrediction", "margins output", ptype=str)
    probabilityCol = Param("probability", "probability output", ptype=str)
    predictionCol = Param("prediction", "label-index output", ptype=str)

    @property
    def num_classes(self) -> int:
        raise NotImplementedError

    def _score(self, X: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(raw, probability, prediction) for a dense feature matrix."""
        raise NotImplementedError

    def transform(self, table: DataTable) -> DataTable:
        X = _features_matrix(table[self.featuresCol])
        raw, prob, pred = self._score(X)
        out = table.with_column(self.rawPredictionCol, np.asarray(raw))
        out = out.with_column(self.probabilityCol, np.asarray(prob))
        return out.with_column(self.predictionCol,
                               np.asarray(pred, np.float64))


class RegressorModel(Transformer):
    featuresCol = Param("features", "features column", ptype=str)
    predictionCol = Param("prediction", "prediction output", ptype=str)

    def _predict(self, X: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, table: DataTable) -> DataTable:
        X = _features_matrix(table[self.featuresCol])
        return table.with_column(self.predictionCol,
                                 np.asarray(self._predict(X), np.float64))


# --------------------------------------------------------------------------
# Logistic regression (binary) — IRLS-class convergence via L-BFGS
# --------------------------------------------------------------------------

class LogisticRegressionModel(ClassifierModel):
    def __init__(self, w: Optional[np.ndarray] = None, b: float = 0.0, **kw):
        super().__init__(**kw)
        self.w = np.asarray(w, np.float32) if w is not None else None
        self.b = float(b)

    @property
    def num_classes(self) -> int:
        return 2

    def _score(self, X):
        z = X @ self.w + self.b
        p = np.asarray(_sigmoid(jnp.asarray(z)))
        raw = np.stack([-z, z], axis=1)
        prob = np.stack([1.0 - p, p], axis=1)
        return raw, prob, (p > 0.5).astype(np.float64)

    def _save_extra(self, path):
        np.savez(os.path.join(path, "coef.npz"), w=self.w, b=self.b)

    def _load_extra(self, path):
        d = np.load(os.path.join(path, "coef.npz"))
        self.w, self.b = d["w"], float(d["b"])


class LogisticRegression(Estimator):
    """Binary logistic regression (Spark's LogisticRegression counterpart;
    multiclass goes through OneVsRest as in TrainClassifier.scala:87-95)."""

    featuresCol = Param("features", "features column", ptype=str)
    labelCol = Param("label", "label column (0/1)", ptype=str)
    regParam = Param(0.0, "L2 regularization strength", ptype=float)
    maxIter = Param(100, "max L-BFGS iterations", ptype=int)
    tol = Param(1e-6, "gradient-norm convergence tolerance", ptype=float)
    fitIntercept = Param(True, "fit an intercept term", ptype=bool)

    def fit(self, table: DataTable) -> LogisticRegressionModel:
        X = _features_matrix(table[self.featuresCol])
        y = np.asarray(table[self.labelCol], np.float32)
        w, b = _fit_binary_lr(jnp.asarray(X), jnp.asarray(y),
                              float(self.regParam), int(self.maxIter),
                              float(self.tol), bool(self.fitIntercept))
        return LogisticRegressionModel(
            np.asarray(w), float(b), featuresCol=self.featuresCol)


def _fit_binary_lr(X, y, reg, max_iter, tol, fit_intercept):
    d = X.shape[1]

    def loss(params):
        w, b = params
        z = X @ w + (b if fit_intercept else 0.0)
        ll = optax.sigmoid_binary_cross_entropy(z, y).mean()
        return ll + 0.5 * reg * jnp.sum(w * w)

    init = (jnp.zeros((d,), jnp.float32), jnp.zeros((), jnp.float32))
    w, b = run_lbfgs(loss, init, max_iter, tol)
    return w, (b if fit_intercept else jnp.zeros(()))


def _fit_binary_lr_multi(X, Y, reg, max_iter, tol, fit_intercept):
    """All K one-vs-rest fits as ONE vmapped L-BFGS: the per-class
    objectives are identical in shape, so a single compile drives K lanes
    on the same matmuls (vs the serial K compile+fit cycles a naive OvR
    loop costs).  Y is (K, N); returns w (K, D), b (K,)."""
    fit_one = lambda yk: _fit_binary_lr(X, yk, reg, max_iter, tol,
                                        fit_intercept)
    return jax.jit(jax.vmap(fit_one))(Y)


class OneVsRestModel(ClassifierModel):
    def __init__(self, models: Optional[list] = None, **kw):
        super().__init__(**kw)
        self._models = list(models or [])

    @property
    def num_classes(self) -> int:
        return len(self._models)

    def _score(self, X):
        # column k = positive-class score of the k-th binary model
        pos = np.stack([m._score(X)[1][:, 1] for m in self._models], axis=1)
        denom = np.maximum(pos.sum(axis=1, keepdims=True), 1e-12)
        prob = pos / denom
        return pos, prob, np.argmax(pos, axis=1).astype(np.float64)

    def _save_extra(self, path):
        for i, m in enumerate(self._models):
            m.save(os.path.join(path, f"class_{i:03d}"))
        with open(os.path.join(path, "n.txt"), "w") as f:
            f.write(str(len(self._models)))

    def _load_extra(self, path):
        with open(os.path.join(path, "n.txt")) as f:
            n = int(f.read())
        self._models = [load_stage(os.path.join(path, f"class_{i:03d}"))
                        for i in range(n)]


class OneVsRest(Estimator):
    """Multiclass reduction over a binary classifier
    (reference TrainClassifier.scala:87-95 wraps LR in Spark's OneVsRest)."""

    featuresCol = Param("features", "features column", ptype=str)
    labelCol = Param("label", "label column (class indices)", ptype=str)

    def __init__(self, classifier: Optional[Estimator] = None, **kw):
        super().__init__(**kw)
        self._classifier = classifier

    def fit(self, table: DataTable) -> OneVsRestModel:
        if self._classifier is None:
            raise ParamError("OneVsRest: no base classifier set")
        y = np.asarray(table[self.labelCol], np.int64)
        n_classes = int(y.max()) + 1 if len(y) else 0
        if type(self._classifier) is LogisticRegression:
            # fast path: one vmapped fit over all classes.  Exact-type gate:
            # a subclass with overridden fit() must take the generic path,
            # not be silently fitted with base-class math
            base = self._classifier
            X = _features_matrix(table[self.featuresCol])
            Y = (y[None, :] == np.arange(n_classes)[:, None]).astype(np.float32)
            w, b = _fit_binary_lr_multi(
                jnp.asarray(X), jnp.asarray(Y), float(base.regParam),
                int(base.maxIter), float(base.tol), bool(base.fitIntercept))
            w, b = np.asarray(w), np.asarray(b)
            models = [LogisticRegressionModel(w[k], float(b[k]),
                                              featuresCol=self.featuresCol)
                      for k in range(n_classes)]
            return OneVsRestModel(models, featuresCol=self.featuresCol)
        models = []
        for k in range(n_classes):
            binary = table.with_column(self.labelCol,
                                       (y == k).astype(np.float32))
            est = self._classifier.copy(featuresCol=self.featuresCol,
                                        labelCol=self.labelCol)
            models.append(est.fit(binary))
        return OneVsRestModel(models, featuresCol=self.featuresCol)


# --------------------------------------------------------------------------
# Linear regression — closed form on device
# --------------------------------------------------------------------------

class LinearRegressionModel(RegressorModel):
    def __init__(self, w: Optional[np.ndarray] = None, b: float = 0.0, **kw):
        super().__init__(**kw)
        self.w = np.asarray(w, np.float32) if w is not None else None
        self.b = float(b)

    def _predict(self, X):
        return X @ self.w + self.b

    def _save_extra(self, path):
        np.savez(os.path.join(path, "coef.npz"), w=self.w, b=self.b)

    def _load_extra(self, path):
        d = np.load(os.path.join(path, "coef.npz"))
        self.w, self.b = d["w"], float(d["b"])


class LinearRegression(Estimator):
    """Ridge/OLS via the normal equations, solved on device in float32
    (the matmul-heavy path XLA maps straight onto the MXU)."""

    featuresCol = Param("features", "features column", ptype=str)
    labelCol = Param("label", "target column", ptype=str)
    regParam = Param(0.0, "L2 regularization", ptype=float)
    fitIntercept = Param(True, "fit an intercept", ptype=bool)

    def fit(self, table: DataTable) -> LinearRegressionModel:
        X = _features_matrix(table[self.featuresCol])
        y = np.asarray(table[self.labelCol], np.float32)
        w, b = _solve_ridge(jnp.asarray(X), jnp.asarray(y),
                            float(self.regParam), bool(self.fitIntercept))
        return LinearRegressionModel(np.asarray(w), float(b),
                                     featuresCol=self.featuresCol)


def _solve_ridge(X, y, reg, fit_intercept):
    # least-squares on X itself (not the normal equations): squaring the
    # condition number in float32 destroys the solve whenever featurization
    # emits collinear blocks (e.g. a one-hot family summing to the
    # intercept); lstsq's min-norm solution stays stable.  Ridge becomes
    # sqrt(lambda) augmentation rows, keeping one code path.
    if fit_intercept:
        mu_x, mu_y = X.mean(0), y.mean()
        Xc, yc = X - mu_x, y - mu_y
    else:
        Xc, yc = X, y
    d = X.shape[1]
    lam = reg * len(y)
    if lam > 0:
        Xc = jnp.concatenate(
            [Xc, jnp.sqrt(lam) * jnp.eye(d, dtype=X.dtype)])
        yc = jnp.concatenate([yc, jnp.zeros((d,), y.dtype)])
    w = jnp.linalg.lstsq(Xc, yc)[0]
    b = (mu_y - mu_x @ w) if fit_intercept else jnp.zeros(())
    return w, b


# --------------------------------------------------------------------------
# Multinomial naive Bayes — native multiclass
# --------------------------------------------------------------------------

class NaiveBayesModel(ClassifierModel):
    def __init__(self, log_prior: Optional[np.ndarray] = None,
                 log_prob: Optional[np.ndarray] = None, **kw):
        super().__init__(**kw)
        self.log_prior = (np.asarray(log_prior, np.float32)
                          if log_prior is not None else None)
        self.log_prob = (np.asarray(log_prob, np.float32)
                         if log_prob is not None else None)

    @property
    def num_classes(self) -> int:
        return len(self.log_prior)

    def _score(self, X):
        raw = X @ self.log_prob.T + self.log_prior
        prob = np.asarray(jax.nn.softmax(jnp.asarray(raw), axis=1))
        return raw, prob, np.argmax(raw, axis=1).astype(np.float64)

    def _save_extra(self, path):
        np.savez(os.path.join(path, "nb.npz"),
                 log_prior=self.log_prior, log_prob=self.log_prob)

    def _load_extra(self, path):
        d = np.load(os.path.join(path, "nb.npz"))
        self.log_prior, self.log_prob = d["log_prior"], d["log_prob"]


class NaiveBayes(Estimator):
    """Multinomial NB with Laplace smoothing (Spark NaiveBayes counterpart;
    requires non-negative features, e.g. hashed counts)."""

    featuresCol = Param("features", "features column (non-negative)", ptype=str)
    labelCol = Param("label", "label column (class indices)", ptype=str)
    smoothing = Param(1.0, "Laplace smoothing", ptype=float)

    def fit(self, table: DataTable) -> NaiveBayesModel:
        X = _features_matrix(table[self.featuresCol])
        if (X < 0).any():
            raise ValueError("NaiveBayes requires non-negative features")
        y = np.asarray(table[self.labelCol], np.int64)
        n_classes = int(y.max()) + 1 if len(y) else 0
        onehot = np.zeros((len(y), n_classes), np.float32)
        onehot[np.arange(len(y)), y] = 1.0
        counts = jnp.asarray(onehot).T @ jnp.asarray(X)  # (C, D)
        alpha = float(self.smoothing)
        smoothed = counts + alpha
        log_prob = jnp.log(smoothed) - jnp.log(
            smoothed.sum(axis=1, keepdims=True))
        class_count = onehot.sum(axis=0)
        log_prior = np.log(np.maximum(class_count, 1e-12) / len(y))
        return NaiveBayesModel(np.asarray(log_prior), np.asarray(log_prob),
                               featuresCol=self.featuresCol)


# --------------------------------------------------------------------------
# Multilayer perceptron — flax module + the distributed Trainer
# --------------------------------------------------------------------------

class MultilayerPerceptronClassifierModel(ClassifierModel):
    def __init__(self, bundle=None, **kw):
        super().__init__(**kw)
        self._bundle = bundle
        self._apply = None

    @property
    def num_classes(self) -> int:
        return self._bundle.module().num_classes

    def _score(self, X):
        if self._apply is None:
            module = self._bundle.module()
            self._apply = jax.jit(lambda v, x: module.apply(v, x))
        raw = np.asarray(self._apply(self._bundle.variables, jnp.asarray(X)))
        prob = np.asarray(jax.nn.softmax(jnp.asarray(raw), axis=1))
        return raw, prob, np.argmax(raw, axis=1).astype(np.float64)

    def _save_extra(self, path):
        from mmlspark_tpu.models.bundle import save_bundle
        save_bundle(self._bundle, os.path.join(path, "bundle"))

    def _load_extra(self, path):
        from mmlspark_tpu.models.bundle import load_bundle
        self._bundle = load_bundle(os.path.join(path, "bundle"))
        self._apply = None


class MultilayerPerceptronClassifier(Estimator):
    """MLP classifier (Spark's MultilayerPerceptronClassifier counterpart,
    TrainClassifier.scala:96-101).  `layers` = [in, hidden..., classes];
    the input size is autosized by TrainClassifier when left as -1."""

    featuresCol = Param("features", "features column", ptype=str)
    labelCol = Param("label", "label column (class indices)", ptype=str)
    layers = Param(None, "layer sizes [input, hidden..., output]",
                   ptype=(list, tuple), required=True)
    maxIter = Param(100, "training epochs", ptype=int)
    stepSize = Param(0.005, "learning rate", ptype=float)
    seed = Param(0, "init/shuffle seed", ptype=int)

    def _fit_inputs(self, table: DataTable):
        """(X, y, trainer config) shared by the single fit and the
        population sweep — both train the IDENTICAL program per member."""
        from mmlspark_tpu.train import TrainerConfig
        self._check_required()
        layers = list(self.layers)
        if len(layers) < 2:
            raise ParamError("layers needs at least [input, output]")
        X = _features_matrix(table[self.featuresCol])
        if layers[0] in (-1, 0, None):
            layers[0] = X.shape[1]
        elif layers[0] != X.shape[1]:
            raise ParamError(f"layers[0]={layers[0]} != feature dim {X.shape[1]}")
        y = np.asarray(table[self.labelCol], np.int64)
        cfg = TrainerConfig(
            architecture="MLPClassifier",
            model_config={"hidden_sizes": layers[1:-1],
                          "num_classes": layers[-1], "dtype": "float32"},
            optimizer="adam", learning_rate=float(self.stepSize),
            epochs=int(self.maxIter),
            batch_size=int(min(max(len(X), 1), 4096)),
            loss="softmax_xent", seed=int(self.seed))
        return X, y, cfg

    def fit(self, table: DataTable) -> MultilayerPerceptronClassifierModel:
        from mmlspark_tpu.train import Trainer
        X, y, cfg = self._fit_inputs(table)
        trainer = Trainer(cfg)
        bundle = trainer.fit_arrays(X, y.astype(np.int32))
        return MultilayerPerceptronClassifierModel(
            bundle, featuresCol=self.featuresCol)

    def fit_population(self, table: DataTable, learning_rates,
                       halving_rungs: int = 0):
        """Train one MLP candidate per learning rate as a vmapped
        population (train/sweep.py) — N models in ONE compiled program —
        then pick the winner by a single batched evaluation: one vmapped
        forward scores every member, one `classification_report_batch`
        ranks them (no per-candidate transform/evaluate round trips).

        Returns (winner model, per-member metrics DataTable ordered like
        `learning_rates`, with `learning_rate`/`final_loss`/`active`
        columns joined on)."""
        from mmlspark_tpu.ml.statistics import classification_report_batch
        from mmlspark_tpu.train import PopulationTrainer
        X, y, cfg = self._fit_inputs(table)
        rates = [float(r) for r in learning_rates]
        if not rates:
            raise ParamError("fit_population needs at least one rate")
        pt = PopulationTrainer(cfg, [{"learning_rate": r} for r in rates],
                               halving_rungs=int(halving_rungs))
        result = pt.fit_arrays(X, y.astype(np.int32))
        logits = pt.score_population(result.state, X)   # (N, rows, classes)
        preds = np.argmax(logits, axis=-1)
        report = classification_report_batch(
            y, preds, model_uids=[f"member_{k}_lr={r:g}"
                                  for k, r in enumerate(rates)])
        acc = np.asarray(report["accuracy"], np.float64)
        ranked = np.where(result.active > 0, acc, -np.inf)
        best = int(np.argmax(ranked))
        report = report.with_column("learning_rate",
                                    np.asarray(rates, np.float64))
        report = report.with_column("final_loss",
                                    result.final_losses().astype(np.float64))
        report = report.with_column("active",
                                    result.active.astype(np.float64))
        model = MultilayerPerceptronClassifierModel(
            result.member_bundle(best), featuresCol=self.featuresCol)
        return model, report
