"""Binary-file enumeration and ingestion.

TPU-native counterpart of the reference's readers
(BinaryFileReader.scala:28-69, HadoopUtils.scala:79-177 SamplePathFilter /
RecursiveFlag, FileUtilities.scala:93-138 ZipIterator): enumerate files
under a path (optionally recursively), sample them by ratio, expand zip
archives into their entries, and load bytes into a DataTable with a
`path` column and a `bytes` column carrying BinaryFileSchema metadata.
"""

from __future__ import annotations

import fnmatch
import os
import zipfile
from typing import Iterator, Optional

import numpy as np

from mmlspark_tpu.core.schema import BinaryFileSchema, ColumnMeta
from mmlspark_tpu.core.table import DataTable, object_column


def list_files(path: str, recursive: bool = False,
               pattern: Optional[str] = None) -> list[str]:
    """Enumerate files under `path` (a file, directory, or glob pattern)."""
    if any(ch in path for ch in "*?["):
        import glob
        return sorted(p for p in glob.glob(path, recursive=recursive)
                      if os.path.isfile(p))
    if os.path.isfile(path):
        return [path]
    out: list[str] = []
    if recursive:
        for root, _, names in os.walk(path):
            out.extend(os.path.join(root, n) for n in names)
    else:
        out = [os.path.join(path, n) for n in os.listdir(path)
               if os.path.isfile(os.path.join(path, n))]
    if pattern:
        out = [p for p in out if fnmatch.fnmatch(os.path.basename(p), pattern)]
    return sorted(out)


def _zip_entries(path: str, sample_ratio: float,
                 rng: np.random.Generator) -> Iterator[tuple[str, bytes]]:
    """Yield (virtual-path, bytes) per zip entry; sampling applies per
    entry, as the reference's ZipIterator + SamplePathFilter does
    (FileUtilities.scala:93-138, BinaryFileReader.scala:43-59)."""
    with zipfile.ZipFile(path) as zf:
        for info in zf.infolist():
            if info.is_dir():
                continue
            if sample_ratio < 1.0 and rng.random() > sample_ratio:
                continue
            yield f"{path}/{info.filename}", zf.read(info)


def iter_binary_files(path: str, recursive: bool = False,
                      sample_ratio: float = 1.0, inspect_zip: bool = True,
                      pattern: Optional[str] = None,
                      seed: int = 0) -> Iterator[tuple[str, bytes]]:
    """Stream (path, bytes) pairs one file at a time — the out-of-core
    ingestion primitive (the reference streams partitions the same way,
    BinaryFileReader.scala:28-69).  Only one file's bytes are resident at a
    time; corpus size is unbounded by host RAM.

    `path` may also be a remote source — ``http(s)://``, ``gs://``,
    ``s3://`` — with identical sampling/zip/pattern semantics (io/remote.py,
    the reference's HDFS/WASB reader seam, AzureBlobReader.scala:12-47);
    `recursive` is meaningless there (object listings are already flat).
    """
    from mmlspark_tpu.io.remote import is_remote, iter_remote_binary_files
    if is_remote(path):
        yield from iter_remote_binary_files(
            path, sample_ratio=sample_ratio, inspect_zip=inspect_zip,
            pattern=pattern, seed=seed)
        return
    if not 0.0 <= sample_ratio <= 1.0:
        raise ValueError(f"sample_ratio must be in [0,1], got {sample_ratio}")
    rng = np.random.default_rng(seed)
    for p in list_files(path, recursive, pattern):
        if inspect_zip and zipfile.is_zipfile(p):
            yield from _zip_entries(p, sample_ratio, rng)
            continue
        if sample_ratio < 1.0 and rng.random() > sample_ratio:
            continue
        with open(p, "rb") as f:
            yield p, f.read()


def read_binary_files(path: str, recursive: bool = False,
                      sample_ratio: float = 1.0, inspect_zip: bool = True,
                      pattern: Optional[str] = None,
                      seed: int = 0) -> DataTable:
    """Read files into a (path, bytes) table.

    sample_ratio subsamples FILES (not bytes), mirroring SamplePathFilter;
    zips are expanded into entries when inspect_zip (ZipIterator).  For
    corpora larger than host RAM use `iter_binary_files` /
    `read_images_iter` instead.
    """
    paths: list[str] = []
    blobs: list[bytes] = []
    for p, data in iter_binary_files(path, recursive, sample_ratio,
                                     inspect_zip, pattern, seed):
        paths.append(p)
        blobs.append(data)
    table = DataTable({"path": object_column(paths),
                       "bytes": object_column(blobs)})
    meta = ColumnMeta(binary=BinaryFileSchema(path_col="path"))
    table.set_meta("bytes", meta)
    return table
