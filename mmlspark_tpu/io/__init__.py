"""IO layer: binary-file and image ingestion (reference L2: readers/)."""

from mmlspark_tpu.io.files import list_files, read_binary_files
from mmlspark_tpu.io.image_reader import decode_bytes, read_images

__all__ = ["list_files", "read_binary_files", "read_images", "decode_bytes"]
