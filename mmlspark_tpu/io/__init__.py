"""IO layer: binary-file, image, remote, and SQL ingestion
(reference L2: readers/)."""

from mmlspark_tpu.io.files import (iter_binary_files, list_files,
                                   read_binary_files)
from mmlspark_tpu.io.image_reader import (decode_bytes, read_images,
                                          read_images_iter)
from mmlspark_tpu.io.sql import iter_sql, read_sql

__all__ = ["list_files", "iter_binary_files", "read_binary_files",
           "read_images", "read_images_iter", "decode_bytes",
           "read_sql", "iter_sql"]
