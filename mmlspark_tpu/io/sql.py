"""SQL ingestion: database queries into DataTables.

TPU-native counterpart of the reference's SQL reader
(AzureSQLReader.scala:12-29, which wrapped Spark's JDBC source; see also
`sqlContext.read.jdbc` usage in Readers.scala:15-50).  The portable seam
here is Python's DB-API 2.0: any conforming connection works — sqlite3
(stdlib), psycopg2, pyodbc against Azure SQL, the BigQuery DB-API, … —
so the reader carries no driver dependency of its own.

Two entry points, mirroring the binary-reader pair:

  * `read_sql(query, conn)`       — one execute, one fetch, one DataTable.
  * `iter_sql(query, conn, n)`    — stream DataTable batches of n rows
    (out-of-core: only one batch of rows is ever resident, the
    BinaryFileReader streaming discipline).

Column typing: `read_sql` infers over the full result — all-numeric
columns become float64 (ints without NULLs stay int64), everything else an
object column with None preserved for SQL NULL.  `iter_sql` must keep
dtypes STABLE across batches (a jitted consumer cannot absorb a mid-stream
dtype flip), so it decides numeric-vs-object from the FIRST batch and
renders every numeric column float64 (NULLs as NaN) for the whole stream;
a later non-numeric value in a numeric column raises.
"""

from __future__ import annotations

from typing import Any, Iterator, Optional

import numpy as np

from mmlspark_tpu.core.table import DataTable, object_column


def _connect(conn: Any):
    """Accept a DB-API connection, a sqlite path/URI, or a zero-arg
    factory returning a connection; returns (connection, owned)."""
    if isinstance(conn, str):
        import sqlite3
        return sqlite3.connect(conn), True
    if callable(conn) and not hasattr(conn, "cursor"):
        return conn(), True
    return conn, False


def _column_array(values: list) -> np.ndarray:
    """Infer one column's array: numeric -> int64/float64, else object."""
    non_null = [v for v in values if v is not None]
    if non_null and all(isinstance(v, (int, float)) and
                        not isinstance(v, bool) for v in non_null):
        if len(non_null) == len(values):
            if all(isinstance(v, int) for v in non_null):
                return np.asarray(values, np.int64)
            return np.asarray(values, np.float64)
        # NULLs force float (NaN holes), the usual dataframe convention
        return np.asarray([np.nan if v is None else float(v)
                           for v in values], np.float64)
    return object_column(values)


def _rows_to_table(names: list[str], rows: list[tuple]) -> DataTable:
    cols = list(zip(*rows)) if rows else [[] for _ in names]
    return DataTable({n: _column_array(list(c))
                      for n, c in zip(names, cols)})


def _is_numeric(values: list) -> bool:
    non_null = [v for v in values if v is not None]
    return bool(non_null) and all(
        isinstance(v, (int, float)) and not isinstance(v, bool)
        for v in non_null)


def _stable_column(values: list, numeric: bool) -> np.ndarray:
    """Stream-stable rendering: numeric -> float64 (NULL as NaN)."""
    if numeric:
        return np.asarray([np.nan if v is None else float(v)
                           for v in values], np.float64)
    return object_column(values)


def iter_sql(query: str, conn: Any, batch_rows: int = 4096,
             params: Optional[tuple] = None) -> Iterator[DataTable]:
    """Stream query results as DataTable batches of `batch_rows`.

    Feeds `TPUModel.transform_batches` directly for score-from-database
    pipelines; the cursor's fetchmany does the windowing, so the database
    result set never materializes on the host at once.  Dtypes are decided
    from the first batch and held STABLE for the whole stream (see module
    docstring) — jitted consumers must not see mid-stream dtype flips.
    """
    if batch_rows <= 0:
        raise ValueError(f"batch_rows must be positive, got {batch_rows}")
    connection, owned = _connect(conn)
    try:
        cur = connection.cursor()
        try:
            cur.execute(query, params or ())
            names = [d[0] for d in cur.description]
            numeric: Optional[list[bool]] = None
            while True:
                rows = [tuple(r) for r in cur.fetchmany(batch_rows)]
                if not rows:
                    break
                cols = [list(c) for c in zip(*rows)]
                if numeric is None:  # schema decided on the first batch
                    numeric = [_is_numeric(c) for c in cols]
                yield DataTable({n: _stable_column(c, isnum)
                                 for n, c, isnum
                                 in zip(names, cols, numeric)})
        finally:
            cur.close()
    finally:
        if owned:
            connection.close()


def read_sql(query: str, conn: Any,
             params: Optional[tuple] = None) -> DataTable:
    """Run `query` once and materialize the full result as one DataTable
    (whole-result type inference: int columns without NULLs stay int64)."""
    connection, owned = _connect(conn)
    try:
        cur = connection.cursor()
        try:
            cur.execute(query, params or ())
            names = [d[0] for d in cur.description]
            return _rows_to_table(names, [tuple(r) for r in cur.fetchall()])
        finally:
            cur.close()
    finally:
        if owned:
            connection.close()
