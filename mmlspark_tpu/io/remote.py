"""Remote binary-file sources: http(s)://, gs://, s3:// ingestion.

TPU-native counterpart of the reference's remote-FS readers — HDFS/WASB
enumeration in `BinaryFileReader.scala:28-69` and the dedicated
`AzureBlobReader.scala:12-47` / `WasbReader.scala:13` — re-targeted at the
object stores a TPU deployment actually sees.  The semantics mirror
`io/files.py` exactly: enumerate, filter by pattern, subsample by
`sample_ratio`, expand zip archives, and stream one file's bytes at a
time (out-of-core by construction).

Listing protocols:
  * ``http(s)://host/path/file``    — a single object.
  * ``http(s)://host/path/``       — a directory: fetches ``MANIFEST``
    (newline-separated relative paths — the zoo repo layout, which
    `LocalRepo.export_manifest` emits, so any repo directory served by a
    plain HTTP server is ingestible).
  * ``gs://bucket/prefix``          — GCS JSON API listing
    (``storage/v1/b/{bucket}/o?prefix=``); optional OAuth bearer token
    from the config registry.
  * ``s3://bucket/prefix``          — S3 ListObjectsV2 (XML).  Anonymous /
    public buckets only: SigV4 signing is deliberately out of scope (use
    pre-signed URLs or an authenticated proxy; docs/design_cuts.md).

Downloads go through one chunked reader (1 MiB ranges of progress, read
timeouts), so a dead link fails fast instead of hanging a scoring
pipeline.  Every fetch runs under the resilience layer (`fetch_url`):
exponential-backoff retries with `Retry-After` honored on 429/503,
fail-fast classification for other 4xx (an auth error should not burn a
backoff budget), and a per-host circuit breaker so a dead endpoint is
refused in milliseconds instead of re-timed-out per object.  The GCS/S3
endpoints are config variables, which is also how the tests drive these
code paths against a local HTTP fixture without network egress.
"""

from __future__ import annotations

import fnmatch
import io
import json
import posixpath
import urllib.parse
import xml.etree.ElementTree as ET
import zipfile
from typing import Iterator, Optional

import numpy as np

from mmlspark_tpu import config
from mmlspark_tpu.resilience.net import fetch_url

_GCS_ENDPOINT = config.register(
    "MMLSPARK_TPU_GCS_ENDPOINT", "https://storage.googleapis.com",
    "GCS API endpoint (override for emulators/tests)")
_GCS_TOKEN = config.register(
    "MMLSPARK_TPU_GCS_TOKEN", None,
    "OAuth2 bearer token for GCS requests (None = anonymous)")
_S3_ENDPOINT = config.register(
    "MMLSPARK_TPU_S3_ENDPOINT", "https://s3.amazonaws.com",
    "S3 API endpoint (override for emulators/tests)")
_TIMEOUT = config.register(
    "MMLSPARK_TPU_REMOTE_TIMEOUT_S", 30.0,
    "per-request connect/read timeout for remote sources", ptype=float)


def is_remote(path: str) -> bool:
    return urllib.parse.urlparse(path).scheme in ("http", "https", "gs",
                                                  "s3")


def _fetch(url: str, headers: Optional[dict] = None) -> bytes:
    """Download under the resilience policy layer: chunked bounded reads
    with a per-request timeout (a stalled link raises instead of wedging
    the ingestion loop), retry/backoff for transient failures, and the
    per-host circuit breaker (resilience/net.py)."""
    return fetch_url(url, headers=headers,
                     timeout=config.get("MMLSPARK_TPU_REMOTE_TIMEOUT_S"))


def _gcs_headers() -> dict:
    token = config.get("MMLSPARK_TPU_GCS_TOKEN")
    return {"Authorization": f"Bearer {token}"} if token else {}


def _list_http(url: str) -> list[tuple[str, str]]:
    """[(display_path, fetch_url)] for an http(s) source."""
    if not url.endswith("/"):
        return [(url, url)]
    manifest = _fetch(urllib.parse.urljoin(url, "MANIFEST")).decode()
    out = []
    for rel in manifest.splitlines():  # newline-separated: paths may
        rel = rel.strip()              # contain spaces
        if not rel or rel.startswith("#"):
            continue
        out.append((urllib.parse.urljoin(url, rel),
                    urllib.parse.urljoin(url, urllib.parse.quote(rel))))
    return out


def _list_gcs(url: str) -> list[tuple[str, str]]:
    parsed = urllib.parse.urlparse(url)
    bucket, prefix = parsed.netloc, parsed.path.lstrip("/")
    endpoint = config.get("MMLSPARK_TPU_GCS_ENDPOINT").rstrip("/")
    names, page = [], None
    while True:
        qs = {"prefix": prefix, "fields": "items(name),nextPageToken"}
        if page:
            qs["pageToken"] = page
        listing = json.loads(_fetch(
            f"{endpoint}/storage/v1/b/{urllib.parse.quote(bucket)}/o?"
            + urllib.parse.urlencode(qs), headers=_gcs_headers()).decode())
        names += [item["name"] for item in listing.get("items", [])]
        page = listing.get("nextPageToken")
        if not page:
            break
    return [(f"gs://{bucket}/{n}",
             f"{endpoint}/storage/v1/b/{urllib.parse.quote(bucket)}/o/"
             f"{urllib.parse.quote(n, safe='')}?alt=media") for n in names]


def _list_s3(url: str) -> list[tuple[str, str]]:
    parsed = urllib.parse.urlparse(url)
    bucket, prefix = parsed.netloc, parsed.path.lstrip("/")
    endpoint = config.get("MMLSPARK_TPU_S3_ENDPOINT").rstrip("/")
    names, token = [], None
    while True:
        qs = {"list-type": "2", "prefix": prefix}
        if token:
            qs["continuation-token"] = token
        root = ET.fromstring(_fetch(
            f"{endpoint}/{urllib.parse.quote(bucket)}?"
            + urllib.parse.urlencode(qs)).decode())
        ns = root.tag.partition("}")[0] + "}" if "}" in root.tag else ""
        names += [c.findtext(f"{ns}Key") for c in root.iter(f"{ns}Contents")]
        token = root.findtext(f"{ns}NextContinuationToken")
        if not token:
            break
    return [(f"s3://{bucket}/{n}",
             f"{endpoint}/{urllib.parse.quote(bucket)}/"
             f"{urllib.parse.quote(n)}") for n in names]


def list_remote_files(path: str,
                      pattern: Optional[str] = None) -> list[tuple[str, str]]:
    """[(display_path, fetch_url)], name-filtered like `list_files`."""
    scheme = urllib.parse.urlparse(path).scheme
    if scheme in ("http", "https"):
        entries = _list_http(path)
    elif scheme == "gs":
        entries = _list_gcs(path)
    elif scheme == "s3":
        entries = _list_s3(path)
    else:
        raise ValueError(f"unsupported remote scheme: {path!r}")
    if pattern:
        entries = [(p, u) for p, u in entries
                   if fnmatch.fnmatch(posixpath.basename(p), pattern)]
    return sorted(entries)


def iter_remote_binary_files(path: str, sample_ratio: float = 1.0,
                             inspect_zip: bool = True,
                             pattern: Optional[str] = None,
                             seed: int = 0) -> Iterator[tuple[str, bytes]]:
    """Remote twin of `iter_binary_files`: stream (path, bytes) with
    identical sample_ratio / zip-expansion / pattern semantics.  One
    file's bytes resident at a time; zip entries are sampled per ENTRY,
    exactly as the local reader (FileUtilities.scala:93-138).  One
    deliberate difference: zips are detected by the ``.zip`` extension —
    content-sniffing a remote object would force downloading files that
    per-file sampling would otherwise skip entirely."""
    if not 0.0 <= sample_ratio <= 1.0:
        raise ValueError(f"sample_ratio must be in [0,1], got {sample_ratio}")
    rng = np.random.default_rng(seed)
    scheme = urllib.parse.urlparse(path).scheme
    headers = _gcs_headers() if scheme == "gs" else {}
    for display, url in list_remote_files(path, pattern):
        if inspect_zip and display.lower().endswith(".zip"):
            data = _fetch(url, headers=headers)
            with zipfile.ZipFile(io.BytesIO(data)) as zf:
                for info in zf.infolist():
                    if info.is_dir():
                        continue
                    if sample_ratio < 1.0 and rng.random() > sample_ratio:
                        continue
                    yield f"{display}/{info.filename}", zf.read(info)
            continue
        if sample_ratio < 1.0 and rng.random() > sample_ratio:
            continue
        yield display, _fetch(url, headers=headers)
