"""Image ingestion: decode files into batched image tensors.

TPU-native counterpart of the reference's ImageReader
(ImageReader.scala:25-62: per-row OpenCV imdecode inside a Spark UDF,
readImages implicits Readers.scala:15-50).  Decode runs host-side through
the C++ codec (native_loader.py; PIL fallback), and the result is *batched*:
uniform-size images (or any images with resize_to) land in one dense
(N, H, W, C) uint8 tensor ready for a single device transfer — the
TPU-first re-design of the reference's one-row-one-struct image schema.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from mmlspark_tpu.core.schema import ColumnMeta, ImageSchema
from mmlspark_tpu.core.table import DataTable, object_column
from mmlspark_tpu.io.files import read_binary_files
from mmlspark_tpu.native_loader import native_decode


def decode_bytes(data: bytes) -> Optional[np.ndarray]:
    """Decode one image buffer to (H, W, C) BGR/gray uint8, or None.

    Tries the C++ decoder first; falls back to PIL for formats it doesn't
    cover (or when the native build is unavailable).
    """
    out = native_decode(data)
    if out is not None:
        return out
    try:
        import io
        from PIL import Image
        img = Image.open(io.BytesIO(data))
        arr = np.asarray(img.convert("L" if img.mode == "L" else "RGB"))
        if arr.ndim == 2:
            return arr[:, :, None]
        return arr[:, :, ::-1].copy()  # RGB -> BGR
    except Exception:
        return None


def read_images(path: str, recursive: bool = False, sample_ratio: float = 1.0,
                inspect_zip: bool = True, resize_to: Optional[tuple] = None,
                drop_failures: bool = True, pattern: Optional[str] = None,
                seed: int = 0) -> DataTable:
    """Read a directory/glob/zip of images into a table.

    Columns: `path`, `image`.  With resize_to=(H, W) (or when every image
    shares one shape) `image` is a dense (N, H, W, C) uint8 tensor with
    ImageSchema metadata; otherwise it is an object column of per-image
    arrays.  Failed decodes are dropped when drop_failures (the reference's
    per-row None filtering, ImageReader.scala:55-59) or raise otherwise.
    """
    files = read_binary_files(path, recursive=recursive,
                              sample_ratio=sample_ratio,
                              inspect_zip=inspect_zip, pattern=pattern,
                              seed=seed)
    paths, images = [], []
    for p, data in zip(files["path"], files["bytes"]):
        img = decode_bytes(data)
        if img is None:
            if drop_failures:
                continue
            raise ValueError(f"could not decode image: {p}")
        images.append(img)
        paths.append(p)

    if resize_to is not None and images:
        from mmlspark_tpu.ops.image import resize
        h, w = resize_to
        # the dense-tensor contract needs one channel count too: widen
        # gray to 3 channels when the set is mixed (OpenCV imdecode's
        # default always-BGR behavior)
        n_channels = {img.shape[2] for img in images}
        if len(n_channels) > 1:
            images = [np.repeat(img, 3, axis=2) if img.shape[2] == 1 else img
                      for img in images]
        # group by source shape so each shape compiles once and the whole
        # group resizes in one batched device dispatch
        by_shape: dict[tuple, list[int]] = {}
        for i, img in enumerate(images):
            by_shape.setdefault(img.shape, []).append(i)
        resized: list = [None] * len(images)
        for shape, idxs in by_shape.items():
            batch = np.stack([images[i] for i in idxs])
            out = np.clip(np.rint(np.asarray(resize(batch, h, w))),
                          0, 255).astype(np.uint8)
            for j, i in enumerate(idxs):
                resized[i] = out[j]
        images = resized

    shapes = {img.shape for img in images}
    if len(shapes) == 1 and images:
        arr = np.stack(images)
        meta = ColumnMeta(image=ImageSchema(
            height=arr.shape[1], width=arr.shape[2], channels=arr.shape[3]))
        table = DataTable({"path": object_column(paths), "image": arr})
        table.set_meta("image", meta)
        return table
    return DataTable({"path": object_column(paths),
                      "image": object_column(images)})
