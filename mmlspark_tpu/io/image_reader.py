"""Image ingestion: decode files into batched image tensors.

TPU-native counterpart of the reference's ImageReader
(ImageReader.scala:25-62: per-row OpenCV imdecode inside a Spark UDF,
readImages implicits Readers.scala:15-50).  Decode runs host-side through
the C++ codec (native_loader.py; PIL fallback), and the result is *batched*:
uniform-size images (or any images with resize_to) land in one dense
(N, H, W, C) uint8 tensor ready for a single device transfer — the
TPU-first re-design of the reference's one-row-one-struct image schema.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from typing import Iterator

from mmlspark_tpu.core.pipeline import check_on_error, record_skipped_rows
from mmlspark_tpu.core.schema import ColumnMeta, ImageSchema
from mmlspark_tpu.core.table import DataTable, object_column
from mmlspark_tpu.data import Dataset
from mmlspark_tpu.io.files import read_binary_files
from mmlspark_tpu.native_loader import native_decode, native_decode_batch
from mmlspark_tpu.observe.spans import active_timings, span_on


def _resolve_on_error(on_error: Optional[str], drop_failures: bool) -> str:
    """Back-compat shim: the legacy drop_failures flag maps onto the
    shared on_error policy ('skip'/'fail'); an explicit on_error wins."""
    if on_error is not None:
        return check_on_error(on_error)
    return "skip" if drop_failures else "fail"


def _pil_decode(data: bytes) -> Optional[np.ndarray]:
    try:
        import io
        from PIL import Image
        img = Image.open(io.BytesIO(data))
        arr = np.asarray(img.convert("L" if img.mode == "L" else "RGB"))
        if arr.ndim == 2:
            return arr[:, :, None]
        return arr[:, :, ::-1].copy()  # RGB -> BGR
    except Exception:
        return None


def decode_bytes(data: bytes) -> Optional[np.ndarray]:
    """Decode one image buffer to (H, W, C) BGR/gray uint8, or None.

    Tries the C++ decoder first; falls back to PIL for formats it doesn't
    cover (or when the native build is unavailable).
    """
    out = native_decode(data)
    if out is not None:
        return out
    return _pil_decode(data)


def _resize_all(images: list, resize_to: tuple) -> list:
    """Shared resize contract of BOTH readers: every image becomes
    (H, W, 3) uint8 — gray widened to 3 channels deterministically (OpenCV
    imdecode's default always-BGR behavior; the streaming reader cannot
    see the whole corpus, so the contract must not depend on it).  Images
    are grouped by source shape so each shape compiles once and resizes in
    one batched device dispatch."""
    from mmlspark_tpu.ops.image import resize
    h, w = resize_to
    fixed = [np.repeat(img, 3, axis=2) if img.shape[2] == 1 else img
             for img in images]
    by_shape: dict[tuple, list[int]] = {}
    for i, img in enumerate(fixed):
        by_shape.setdefault(img.shape, []).append(i)
    out: list = [None] * len(fixed)
    for _, idxs in by_shape.items():
        batch = np.stack([fixed[i] for i in idxs])
        res = np.clip(np.rint(np.asarray(resize(batch, h, w))),
                      0, 255).astype(np.uint8)
        for j, i in enumerate(idxs):
            out[i] = res[j]
    return out


def decode_many(buffers: list) -> list:
    """Decode a batch of image buffers; None per undecodable entry.

    The C++ thread-pool path (native_decode_batch) decodes the whole batch
    in parallel outside the GIL — the data-loader hot path; entries it
    can't handle (exotic formats, no native lib) retry through the
    per-item `decode_bytes` PIL fallback."""
    native = native_decode_batch(buffers)
    if native is None:
        return [decode_bytes(b) for b in buffers]
    # the batch call already proved the None entries native-undecodable —
    # retry them through PIL only, not through a second native probe
    return [img if img is not None else _pil_decode(buffers[i])
            for i, img in enumerate(native)]


def read_images(path: str, recursive: bool = False, sample_ratio: float = 1.0,
                inspect_zip: bool = True, resize_to: Optional[tuple] = None,
                drop_failures: bool = True, pattern: Optional[str] = None,
                seed: int = 0, on_error: Optional[str] = None) -> DataTable:
    """Read a directory/glob/zip of images into a table.

    Columns: `path`, `image`.  With resize_to=(H, W) `image` is a dense
    (N, H, W, 3) uint8 tensor — ALWAYS 3 channels, grayscale widened
    (the deterministic contract shared with `read_images_iter`).  Without
    resize_to, uniform-shape corpora produce a dense (N, H, W, C) tensor
    with ImageSchema metadata and mixed shapes fall back to an object
    column of per-image arrays.

    Failed decodes follow the `on_error` policy (core/pipeline.py):
    "skip" drops the row (the reference's per-row None filtering,
    ImageReader.scala:55-59), "fail" raises, "column" keeps every row —
    the bad row's image is an all-zero placeholder and the message lands
    in a `decode_error` object column (None for healthy rows), so one
    undecodable image no longer aborts or silently shrinks a batch.
    Default: the legacy `drop_failures` flag (True -> "skip",
    False -> "fail").
    """
    policy = _resolve_on_error(on_error, drop_failures)
    files = read_binary_files(path, recursive=recursive,
                              sample_ratio=sample_ratio,
                              inspect_zip=inspect_zip, pattern=pattern,
                              seed=seed)
    paths, images, errors = [], [], []
    skipped = 0
    decoded = decode_many(list(files["bytes"]))
    for p, img in zip(files["path"], decoded):
        if img is None:
            if policy == "skip":
                skipped += 1
                continue
            if policy == "fail":
                raise ValueError(f"could not decode image: {p}")
            images.append(None)  # placeholder filled once a shape is known
            paths.append(p)
            errors.append(f"could not decode image: {p}")
            continue
        images.append(img)
        paths.append(p)
        errors.append(None)
    # skipped rows are never silent at the run level: counter + event
    record_skipped_rows("read_images", skipped, "undecodable image")

    if policy == "column":
        shapes = [img.shape for img in images if img is not None]
        fill_shape = ((resize_to + (3,)) if resize_to is not None
                      else (shapes[0] if shapes else (1, 1, 3)))
        images = [np.zeros(fill_shape, np.uint8) if img is None else img
                  for img in images]

    if resize_to is not None and images:
        images = _resize_all(images, resize_to)

    shapes = {img.shape for img in images}
    if len(shapes) == 1 and images:
        arr = np.stack(images)
        meta = ColumnMeta(image=ImageSchema(
            height=arr.shape[1], width=arr.shape[2], channels=arr.shape[3]))
        table = DataTable({"path": object_column(paths), "image": arr})
        table.set_meta("image", meta)
    else:
        table = DataTable({"path": object_column(paths),
                           "image": object_column(images)})
    if policy == "column":
        table = table.with_column("decode_error", object_column(errors))
    return table


def service_decode_chunk(chunk: list) -> tuple:
    """Decode one `(path, bytes)` chunk to `([paths], [arrays|None])` —
    the module-level (graph-serializable) form of the decode stage, so
    `read_images_iter(service=...)` can ship it to data-service workers
    by import reference (data/graph.py).  Per-row `on_error` policy is
    NOT applied here: it stays on the consumer thread (`absorb`), so
    failures surface in row order whichever process decoded them."""
    return [p for p, _ in chunk], decode_many([b for _, b in chunk])


def _dense_batch(paths: list, images: list,
                 errors: Optional[list] = None) -> DataTable:
    arr = np.stack(images)
    table = DataTable({"path": object_column(paths), "image": arr})
    table.set_meta("image", ColumnMeta(image=ImageSchema(
        height=arr.shape[1], width=arr.shape[2], channels=arr.shape[3])))
    if errors is not None:
        table = table.with_column("decode_error", object_column(errors))
    return table


def read_images_iter(path: str, batch_size: int = 256,
                     recursive: bool = False, sample_ratio: float = 1.0,
                     inspect_zip: bool = True,
                     resize_to: Optional[tuple] = None,
                     drop_failures: bool = True,
                     pattern: Optional[str] = None,
                     seed: int = 0,
                     on_error: Optional[str] = None,
                     service=None,
                     deterministic: bool = True) -> Iterator[DataTable]:
    """Stream a directory/glob/zip of images as dense fixed-shape batches.

    The out-of-core face of `read_images` (reference streams partitions,
    BinaryFileReader.scala:28-69): yields (path, image) tables of at most
    `batch_size` rows, decoding batch-at-a-time (the parallel C++ decoder)
    — peak residency is one batch of encoded buffers plus up to ~2 batches
    of decoded pixels, so corpus size is unbounded by host RAM.  Feed the
    result to `TPUModel.transform_batches` for streaming scoring.

    Every batch is dense (N, H, W, C) uint8: with resize_to=(H, W) decoded
    images are batch-resized on device to (H, W, 3) — the same
    deterministic 3-channel contract as `read_images` — while without it
    all images must share one shape (a shape mismatch raises; streaming
    cannot re-group shapes after the fact the way the materializing reader
    does).

    Failed decodes follow `on_error` exactly like `read_images` — with
    the one streaming caveat that "column" without resize_to needs a
    decodable image (or resize_to) before the first failure, since the
    placeholder must match the stream's fixed shape.

    `service` splices the disaggregated data service into the decode
    path: pass a `data.service.DataService` and the read+decode graph
    executes on its worker processes (`Dataset.distribute`), while
    per-row policy, resize, and batch assembly stay on the consumer.
    `deterministic=True` (default) keeps batch order byte-identical to
    local execution; False takes first-come dynamic sharding.
    """
    policy = _resolve_on_error(on_error, drop_failures)
    if batch_size <= 0:
        raise ValueError(f"batch_size must be positive, got {batch_size}")
    paths: list = []
    images: list = []
    errors: list = []
    first_shape: Optional[tuple] = None

    def decode_batch(chunk):
        # runs on the Dataset map workers: the NEXT batch decodes (C++
        # pool / PIL fallback) while the consumer resizes, assembles,
        # and the caller scores the current one.  Per-row policy checks
        # stay on the consumer thread so failures surface in row order.
        with span_on(timings, "host"):
            return [p for p, _ in chunk], decode_many([b for _, b in chunk])

    def absorb(batch_paths: list, decoded: list) -> None:
        nonlocal first_shape
        skipped = 0
        for p, img in zip(batch_paths, decoded):
            if img is None:
                if policy == "skip":
                    skipped += 1
                    continue
                if policy == "fail":
                    raise ValueError(f"could not decode image: {p}")
                if resize_to is not None:
                    img = np.zeros(resize_to + (3,), np.uint8)
                elif first_shape is not None:
                    img = np.zeros(first_shape, np.uint8)
                else:
                    raise ValueError(
                        f"on_error='column' placeholder for {p} needs a "
                        "known shape: pass resize_to or ensure the stream "
                        "starts with a decodable image")
                errors.append(f"could not decode image: {p}")
            else:
                errors.append(None)
            if resize_to is None:
                if first_shape is None:
                    first_shape = img.shape
                elif img.shape != first_shape:
                    raise ValueError(
                        f"streaming without resize_to needs uniform shapes; "
                        f"{p} is {img.shape}, stream started with "
                        f"{first_shape}")
            paths.append(p)
            images.append(img)
        # per decode-batch, on the consumer thread (row-order preserved)
        record_skipped_rows("read_images_iter", skipped,
                            "undecodable image")

    def flush(k: int) -> DataTable:
        nonlocal paths, images, errors
        batch, keep = images[:k], images[k:]
        batch_paths, paths = paths[:k], paths[k:]
        batch_errors, errors = errors[:k], errors[k:]
        images = keep
        return _dense_batch(
            batch_paths, _resize_all(batch, resize_to)
            if resize_to is not None else batch,
            batch_errors if policy == "column" else None)

    timings = active_timings()
    # Dataset graph over the file stream: enumeration + reads stay
    # sequential on the pulling thread (ordering contract), decode runs
    # on bounded parallel map workers — peak residency is `depth` decoded
    # batches plus the accumulation buffer, so corpora stay unbounded by
    # host RAM.  The depth knob (MMLSPARK_TPU_PREFETCH_DEPTH) pins the
    # lookahead when positive and hands it to the Autotuner when 0.
    source = Dataset.from_files(path, recursive=recursive,
                                sample_ratio=sample_ratio,
                                inspect_zip=inspect_zip, pattern=pattern,
                                seed=seed).batch(batch_size)
    if service is not None:
        # service path: the serializable module-level decode fn replaces
        # the span-instrumented closure (workers can't see this run's
        # timings contextvar anyway) and the graph below this point runs
        # on the service's worker processes
        staged = (source
                  .map(service_decode_chunk, name="decode", span=None)
                  .distribute(service, deterministic=deterministic)
                  .iterator())
    else:
        staged = (source
                  .map(decode_batch, name="decode", span=None)
                  .iterator())
    try:
        for batch_paths, decoded in staged:
            absorb(batch_paths, decoded)
            while len(images) >= batch_size:
                yield flush(batch_size)
        while images:
            yield flush(batch_size)
    finally:
        staged.close()
