# mmlspark_tpu development/CI image (the reference's tools/docker/Dockerfile
# analogue: its image bundled Spark+CNTK+OpenCV; here the stack is
# pip-resolvable and the only system deps are the C++ toolchain and image
# codec headers for the native decoder).
#
#   docker build -t mmlspark_tpu .
#   docker run --rm mmlspark_tpu                    # run the gate
#   docker run --rm -it mmlspark_tpu bash           # dev shell
#
# On TPU VMs, base on an image with the libtpu stack instead and install
# jax[tpu]; this image runs the 8-virtual-device CPU mesh.
FROM python:3.11-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make libjpeg-dev libpng-dev \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /opt/mmlspark_tpu
COPY pyproject.toml README.md ./
COPY mmlspark_tpu ./mmlspark_tpu
COPY tests ./tests
COPY examples ./examples
COPY scripts ./scripts
COPY docs ./docs
COPY bench.py __graft_entry__.py Makefile ./

RUN pip install --no-cache-dir jax flax optax chex einops numpy pytest pillow \
    && pip install --no-cache-dir -e . --no-deps --no-build-isolation

# build the native decoder at image build time (fails soft to PIL)
RUN python -c "from mmlspark_tpu import native_loader; native_loader.build_native()" || true

CMD ["bash", "scripts/check.sh"]
