"""301 - CIFAR-10 ConvNet evaluation.

Mirrors the reference's notebook 301 (`notebooks/samples/301 - CIFAR10 CNTK
CNN Evaluation.ipynb`): load the zoo ConvNet, score an image table through
TPUModel (the CNTKModel counterpart), and evaluate with
ComputeModelStatistics including the confusion matrix.  The reference
downloaded a pretrained CNTK graph; air-gapped here, the zoo model is
fine-tuned on the synthetic set first (train/ is the cntk-train
counterpart), then evaluated exactly as the notebook does — the notebook's
timed scoring loop becomes the bench.py throughput measurement.
"""

import time

import numpy as np

from mmlspark_tpu import stage_timing
from mmlspark_tpu.core.schema import SchemaConstants, set_score_column
from mmlspark_tpu.ml import ComputeModelStatistics
from mmlspark_tpu.models import TPUModel
from mmlspark_tpu.train import TPULearner, TrainerConfig
from mmlspark_tpu.utils.demo_data import cifar_like
from mmlspark_tpu.zoo import ModelDownloader, create_builtin_repo


def main(verbose: bool = True, out_dir: str = "/tmp/mmlspark_tpu_zoo") -> dict:
    with stage_timing() as times:
        result = _run(verbose, out_dir)
    if verbose:
        print("\nstage times:\n" + times.table())
    result["stage_times"] = times.records
    return result


def _run(verbose: bool, out_dir: str) -> dict:
    log = print if verbose else (lambda *a, **k: None)
    data = cifar_like(n=512, seed=3)
    n_train = 384
    train = data.slice(0, n_train)
    test = data.slice(n_train, data.num_rows)

    # zoo model (downloader counterpart)
    repo = create_builtin_repo(out_dir, include=["ConvNet"])
    dl = ModelDownloader(f"{out_dir}_cache")
    schema = dl.download_by_name(repo, "ConvNet")
    bundle = dl.load_bundle(schema)
    log(f"zoo model: {schema.name} ({schema.size} bytes, "
        f"layers {schema.layerNames})")

    # fine-tune on the synthetic classes
    cfg = TrainerConfig(
        architecture=bundle.architecture,
        model_config=bundle.config,
        optimizer="momentum", learning_rate=0.003, epochs=6, batch_size=64,
        loss="softmax_xent", seed=0)
    features = train["image"].astype(np.float32) / 255.0
    model = TPULearner(cfg).set_initial_bundle(bundle).fit(
        train.drop("image", "label")
             .with_column("features", features)
             .with_column("label", np.asarray(train["label"], np.int32)))

    # score the eval set (the notebook's timed loop)
    scorer = TPUModel(model.bundle, inputCol="image", outputCol="scores",
                      miniBatchSize=128)
    t0 = time.perf_counter()
    scored = scorer.transform(
        test.with_column("image", test["image"].astype(np.float32) / 255.0))
    wall = time.perf_counter() - t0
    preds = np.argmax(scored["scores"], axis=1).astype(np.float64)
    scored = scored.with_column("prediction", preds)
    set_score_column(scored, "example301", "prediction",
                     SchemaConstants.SCORED_LABELS_COLUMN,
                     SchemaConstants.CLASSIFICATION_KIND)
    set_score_column(scored, "example301", "label",
                     SchemaConstants.TRUE_LABELS_COLUMN,
                     SchemaConstants.CLASSIFICATION_KIND)

    result = ComputeModelStatistics().evaluate(scored)
    acc = float(result.metrics["accuracy"][0])
    log(f"eval: {test.num_rows} images in {wall:.2f}s "
        f"({test.num_rows / wall:.0f} img/s), accuracy={acc:.3f}")
    log(f"confusion matrix diag: {np.diag(result.confusion_matrix)}")
    return {"accuracy": acc, "images_per_s": test.num_rows / wall,
            "confusion_matrix": result.confusion_matrix}


if __name__ == "__main__":
    main()
