"""301 - pretrained ConvNet evaluation (the reference's notebook 301 flow).

Mirrors `notebooks/samples/301 - CIFAR10 CNTK CNN Evaluation.ipynb`: fetch a
REAL pretrained model through the downloader (sha256-verified into a local
cache, ModelDownloader.scala:109-157), score a held-out image table through
TPUModel (the CNTKModel counterpart), and evaluate with
ComputeModelStatistics including the confusion matrix — the notebook's
timed scoring loop becomes the bench.py throughput measurement.

The model is the package zoo's ConvNet/UCIDigits: the flagship
ConvNetCIFAR10 architecture trained by scripts/train_zoo_model.py on the
real UCI handwritten-digits images (CIFAR-10's raw archive needs network
egress this build does not have — docs/design_cuts.md).  Accuracy here is
genuine held-out accuracy of trained weights, the counterpart of the
reference's pretrained ConvNet_CIFAR10.model fixture
(CNTKTestUtils.scala:12-36).
"""

import time

import numpy as np

from mmlspark_tpu import DataTable, stage_timing
from mmlspark_tpu.core.schema import SchemaConstants, set_score_column
from mmlspark_tpu.ml import ComputeModelStatistics
from mmlspark_tpu.models import TPUModel
from mmlspark_tpu.utils.demo_data import digits_images
from mmlspark_tpu.zoo import ModelDownloader, pretrained_repo


def main(verbose: bool = True,
         out_dir: str = "/tmp/mmlspark_tpu_zoo_cache") -> dict:
    log = print if verbose else (lambda *a, **k: None)

    # the REAL held-out digits split the zoo model never trained on
    _, _, x_test, y_test = digits_images()
    test = DataTable({"image": x_test,
                      "label": y_test.astype(np.float64)})

    # zoo model: sha256-verified download into the local cache
    repo = pretrained_repo()
    dl = ModelDownloader(out_dir)
    schema = dl.download_by_name(repo, "ConvNet")
    bundle = dl.load_bundle(schema)
    log(f"zoo model: {schema.name}/{schema.dataset} ({schema.size} bytes, "
        f"layers {schema.layerNames}, "
        f"published test accuracy {bundle.metadata.get('test_accuracy')})")

    # score the eval set under the stage timer (the notebook's timed
    # scoring loop); uint8 images travel the link at 1 byte/pixel and
    # TPUModel casts on device
    scorer = TPUModel(bundle, inputCol="image", outputCol="scores",
                      miniBatchSize=128)
    with stage_timing() as times:
        t0 = time.perf_counter()
        scored = scorer.transform(test)
        wall = time.perf_counter() - t0
    preds = np.argmax(scored["scores"], axis=1).astype(np.float64)
    scored = scored.with_column("prediction", preds)
    set_score_column(scored, "example301", "prediction",
                     SchemaConstants.SCORED_LABELS_COLUMN,
                     SchemaConstants.CLASSIFICATION_KIND)
    set_score_column(scored, "example301", "label",
                     SchemaConstants.TRUE_LABELS_COLUMN,
                     SchemaConstants.CLASSIFICATION_KIND)

    # evaluate: accuracy + the full confusion matrix, metadata-driven
    result = ComputeModelStatistics().evaluate(scored)
    acc = float(result.metrics["accuracy"][0])
    log(f"eval: {test.num_rows} real images in {wall:.2f}s "
        f"({test.num_rows / wall:.0f} img/s), held-out accuracy={acc:.3f}")
    log(f"confusion matrix diag: {np.diag(result.confusion_matrix)}")
    log("\nstage times:\n" + times.table())
    return {"accuracy": acc, "n_test": test.num_rows,
            "images_per_s": test.num_rows / wall,
            "confusion_matrix": result.confusion_matrix,
            "stage_times": times.records}


if __name__ == "__main__":
    main()
