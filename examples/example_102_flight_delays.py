"""102 - Regression with a flight-delay-shaped dataset.

Mirrors the reference's notebook 102 (`notebooks/samples/102 - Regression
Example with Flight Delay Dataset.ipynb`): TrainRegressor over mixed
numeric/categorical features, metric evaluation with
ComputeModelStatistics, and per-row losses with
ComputePerInstanceStatistics.
"""

import numpy as np

from mmlspark_tpu.ml import (
    ComputeModelStatistics,
    ComputePerInstanceStatistics,
    GBTRegressor,
    LinearRegression,
    RandomForestRegressor,
    TrainRegressor,
)
from mmlspark_tpu.utils.demo_data import flight_delays_like


def main(verbose: bool = True) -> dict:
    log = print if verbose else (lambda *a, **k: None)
    data = flight_delays_like(n=800, seed=1)
    n_train = 600
    train = data.slice(0, n_train)
    test = data.slice(n_train, data.num_rows)
    log(f"flight-delay-like data: {data.num_rows} rows")

    learners = {
        "LinearRegression": LinearRegression(),
        "RandomForest": RandomForestRegressor(numTrees=10, maxDepth=5),
        "GBT": GBTRegressor(maxIter=15, maxDepth=4),
    }
    results = {}
    per_instance = None
    for name, learner in learners.items():
        model = TrainRegressor(learner, labelCol="arr_delay").fit(train)
        scored = model.transform(test)
        metrics = ComputeModelStatistics().transform(scored)
        results[name] = {c: float(metrics[c][0]) for c in metrics.columns}
        log(f"  {name}: rmse={results[name]['root_mean_squared_error']:.2f} "
            f"R^2={results[name]['R^2']:.3f}")
        if per_instance is None:
            per_instance = ComputePerInstanceStatistics().transform(scored)
    assert per_instance is not None and "L2_loss" in per_instance.columns
    return {"metrics": results,
            "mean_l2": float(np.mean(per_instance["L2_loss"]))}


if __name__ == "__main__":
    main()
