"""401 - Language-model training + KV-cache text generation.

Pure new-design headroom over the reference (which has no language model
or sequence axis at all — SURVEY §2b): train a small decoder-only
TransformerLM on a repeating character corpus through the SAME Trainer
surface every other model uses, then generate continuations through the
jit-once KV-cache decode program (models/generate.py) — prefill writes
every layer's K/V once, a `lax.scan` decodes one token per tick with no
per-step dispatch, and greedy decoding provably matches the
recompute-everything oracle (tests/test_generate.py).

On real hardware the same model family runs flash attention, ring
sequence parallelism, MoE experts, and pipeline stages (docs/
parallelism.md); this example keeps dense float32 blocks so its pinned
metrics are exactly reproducible on the CPU test mesh.
"""

import numpy as np

from mmlspark_tpu import DataTable
from mmlspark_tpu.models import TextGenerator, naive_generate
from mmlspark_tpu.train import Trainer, TrainerConfig

VOCAB = 16
SEQ = 24
PROMPT_LEN = 8
MAX_NEW = 12


def _char_corpus(n_rows: int = 64) -> np.ndarray:
    """A fully learnable corpus: rows cycle the vocabulary from a random
    phase, so next-token prediction has one right answer per position.
    Rows carry SEQ+1 tokens — inputs and targets are SLICES, so the last
    supervised position's target is the true cycle continuation (np.roll
    would wrap a contradictory target there, SEQ not being a multiple of
    VOCAB)."""
    rng = np.random.default_rng(41)
    starts = rng.integers(0, VOCAB, size=(n_rows, 1))
    return ((starts + np.arange(SEQ + 1)) % VOCAB).astype(np.int32)


def main(verbose: bool = True) -> dict:
    log = print if verbose else (lambda *a, **k: None)

    # next-token training data: inputs and their one-step shift
    rows = _char_corpus()
    tokens, targets = rows[:, :-1], rows[:, 1:]
    log(f"corpus: {tokens.shape[0]} rows of {SEQ} tokens, vocab {VOCAB}")

    # train the LM through the ordinary Trainer surface (same config
    # object that drives TP/EP/PP at scale)
    trainer = Trainer(TrainerConfig(
        architecture="TransformerLM",
        model_config={"vocab_size": VOCAB, "d_model": 32, "n_heads": 4,
                      "n_layers": 2, "max_len": SEQ + 16,
                      "dtype": "float32"},
        optimizer="adam", learning_rate=3e-3, lr_schedule="cosine",
        epochs=30, batch_size=32, loss="softmax_xent", seed=0,
        shuffle_each_epoch=False))
    bundle = trainer.fit_arrays(tokens, targets)
    final_loss = trainer.history[-1]["loss"]
    log(f"trained: epoch-{len(trainer.history) - 1} loss {final_loss:.4f}")

    # generate continuations with the KV-cache decode engine: a
    # TextGenerator stage over a table of prompts (prompts are bucketed —
    # a handful of compiled shape classes serve any mix of lengths)
    prompts = tokens[:4, :PROMPT_LEN]
    gen = TextGenerator(bundle, inputCol="prompt", outputCol="generated",
                        maxNewTokens=MAX_NEW)
    out = gen.transform(DataTable({"prompt": prompts}))["generated"]
    log(f"generated: {out.shape[0]} rows of {out.shape[1]} tokens")

    # the learned rule is "count, wrapping at the vocab": score greedy
    # continuations against the true cycle
    expect = (prompts[:, -1:] + 1 + np.arange(MAX_NEW)) % VOCAB
    continuation_accuracy = float((out[:, PROMPT_LEN:] == expect).mean())
    log(f"continuation accuracy vs the true cycle: "
        f"{continuation_accuracy:.3f}")

    # the cache is an optimization, never a semantics change: greedy
    # decode equals the recompute-everything oracle
    oracle = naive_generate(bundle.module(), bundle.variables, prompts,
                            MAX_NEW)
    assert (out == oracle).all(), "KV-cache decode diverged from oracle"
    log("KV-cache decode matches the recompute oracle exactly")

    return {"final_loss": final_loss,
            "continuation_accuracy": continuation_accuracy,
            "n_generated": int(out.shape[0] * (out.shape[1] - PROMPT_LEN))}


if __name__ == "__main__":
    main()
