"""201 - Book reviews with TextFeaturizer.

Mirrors the reference's notebook 201 (`notebooks/samples/201 - Amazon Book
Reviews - TextFeaturizer.ipynb`): featurize free text with the
TextFeaturizer chain (tokenize -> stop words -> n-grams -> hashing TF ->
IDF), densify, and train a classifier on the result.
"""


from mmlspark_tpu.feature import TextFeaturizer, densify_sparse_column
from mmlspark_tpu.ml import ComputeModelStatistics, LogisticRegression, TrainClassifier
from mmlspark_tpu.utils.demo_data import book_reviews_like


def main(verbose: bool = True) -> dict:
    log = print if verbose else (lambda *a, **k: None)
    data = book_reviews_like(n=400, seed=2)
    n_train = 300
    train = data.slice(0, n_train)
    test = data.slice(n_train, data.num_rows)
    log(f"book-review-like data: {data.num_rows} rows; "
        f"sample: {train['text'][0][:60]!r}")

    featurizer = TextFeaturizer(
        inputCol="text", outputCol="feats",
        useStopWordsRemover=True, useIDF=True,
        numFeatures=1 << 14).fit(train)

    def densify(t):
        out = featurizer.transform(t)
        dense = densify_sparse_column(
            out["feats"], num_features=1 << 14)
        # keep only the label + dense features for training
        return out.drop("feats", "text").with_column("feats", dense)

    model = TrainClassifier(LogisticRegression(), labelCol="rating").fit(
        densify(train))
    metrics = ComputeModelStatistics().transform(model.transform(densify(test)))
    out = {c: float(metrics[c][0]) for c in metrics.columns}
    log(f"test metrics: { {k: round(v, 4) for k, v in out.items()} }")
    return out


if __name__ == "__main__":
    main()
