"""302 - Pipeline image transformations + transfer learning.

Mirrors the reference's notebook 302 (`notebooks/samples/302 - Pipeline
Image Transformations.ipynb`): read images from REMOTE storage over HTTP
(`read_images` against an http:// source — the counterpart of the
notebook's wasb:// reads, BinaryFileReader.scala:28-69 /
AzureBlobReader.scala:12-47; a local HTTP server stands in for the blob
store), run batched ImageTransformer ops (resize, crop, flip — the OpenCV
stage pipeline), featurize with the TRAINED zoo
ResNet's bottleneck pool layer (ImageFeaturizer over ResNetDigits — the
reference's transfer suite ran a real ResNet50 the same way,
ImageFeaturizerSuite.scala:45-53), and train a classifier on the
features.
"""

import http.server
import os
import shutil
import tempfile
import threading

import numpy as np

from mmlspark_tpu.io import read_images
from mmlspark_tpu.ml import ComputeModelStatistics, LogisticRegression, TrainClassifier
from mmlspark_tpu.utils.demo_data import cifar_like
from mmlspark_tpu.vision import ImageFeaturizer, ImageTransformer
from mmlspark_tpu.zoo import ModelDownloader, pretrained_repo


def _write_image_dir(root: str, n: int = 96) -> int:
    """Materialize a 2-class image directory tree plus the MANIFEST that
    makes it HTTP-servable (the zoo-repo listing convention)."""
    from PIL import Image
    data = cifar_like(n=n, seed=5, n_classes=2)
    labels = np.asarray(data["label"], np.int64)
    rels = []
    for i in range(n):
        rel = f"class{labels[i]}/img{i:03d}.png"
        os.makedirs(os.path.join(root, os.path.dirname(rel)), exist_ok=True)
        arr = data["image"][i][:, :, ::-1]  # BGR -> RGB for PIL
        Image.fromarray(arr).save(os.path.join(root, rel))
        rels.append(rel)
    with open(os.path.join(root, "MANIFEST"), "w") as f:
        f.write("\n".join(rels) + "\n")
    return n


def _read_over_http(root: str):
    """Serve `root` on a loopback HTTP port and ingest it REMOTELY: the
    same read_images call a gs://-bucket deployment uses (io/remote.py)."""
    class _Quiet(http.server.SimpleHTTPRequestHandler):
        def __init__(self, *a, **kw):
            super().__init__(*a, directory=root, **kw)

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Quiet)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{httpd.server_port}/"
        return read_images(url, pattern="*.png")
    finally:
        httpd.shutdown()
        httpd.server_close()


def main(verbose: bool = True) -> dict:
    log = print if verbose else (lambda *a, **k: None)

    # stage a 2-class image corpus and ingest it over HTTP: remote-storage
    # reads are the reference notebook's wasb:// path (a loopback server
    # stands in for the blob store)
    root = tempfile.mkdtemp()
    n = _write_image_dir(root, n=96)
    table = _read_over_http(root)
    log(f"read {table.num_rows}/{n} images over HTTP "
        f"-> dense tensor {table['image'].shape}")
    labels = np.asarray(
        [0.0 if "class0" in p else 1.0 for p in table["path"]])
    table = table.with_column("label", labels)

    # batched transformer ops (the OpenCV stage pipeline)
    transformed = (ImageTransformer(inputCol="image", outputCol="image")
                   .resize(40, 40).center_crop(32, 32).flip()
                   .transform(table))
    assert transformed["image"].shape[1:] == (32, 32, 3)

    # transfer learning via the TRAINED zoo ResNet's bottleneck pool
    # features (cutOutputLayers=1 -> the 128-dim global-average node)
    dl = ModelDownloader(os.path.join(root, "cache"))
    bundle = dl.load_bundle(
        dl.download_by_name(pretrained_repo(), "ResNetDigits"))
    feats = ImageFeaturizer(bundle, inputCol="image",
                            outputCol="features",
                            cutOutputLayers=1).transform(transformed)
    log(f"featurized: {feats['features'].shape}")

    # train a classifier on the transferred features, evaluate held-out
    train = feats.slice(0, 72)
    test = feats.slice(72, feats.num_rows)
    model = TrainClassifier(LogisticRegression(), labelCol="label").fit(
        train.drop("image", "path"))
    metrics = ComputeModelStatistics().transform(
        model.transform(test.drop("image", "path")))
    acc = float(metrics["accuracy"][0])
    log(f"transfer-learning accuracy: {acc:.3f}")
    shutil.rmtree(root, ignore_errors=True)  # staged corpus + model cache
    return {"n_images": table.num_rows, "accuracy": acc,
            "feature_dim": feats["features"].shape[1]}


if __name__ == "__main__":
    main()
