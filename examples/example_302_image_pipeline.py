"""302 - Pipeline image transformations + transfer learning.

Mirrors the reference's notebook 302 (`notebooks/samples/302 - Pipeline
Image Transformations.ipynb`): read images from disk (`read_images`, the
readImages counterpart), run batched ImageTransformer ops (resize, crop,
flip — the OpenCV stage pipeline), featurize with a truncated zoo model
(ImageFeaturizer), and train a classifier on the features.
"""

import os
import tempfile

import numpy as np

from mmlspark_tpu.io import read_images
from mmlspark_tpu.ml import ComputeModelStatistics, LogisticRegression, TrainClassifier
from mmlspark_tpu.utils.demo_data import cifar_like
from mmlspark_tpu.vision import ImageFeaturizer, ImageTransformer
from mmlspark_tpu.zoo import ModelDownloader, create_builtin_repo


def _write_image_dir(root: str, n: int = 96) -> int:
    """Materialize a synthetic 2-class image directory tree (the notebook
    reads a folder of files)."""
    from PIL import Image
    data = cifar_like(n=n, seed=5, n_classes=2)
    labels = np.asarray(data["label"], np.int64)
    for i in range(n):
        cls_dir = os.path.join(root, f"class{labels[i]}")
        os.makedirs(cls_dir, exist_ok=True)
        arr = data["image"][i][:, :, ::-1]  # BGR -> RGB for PIL
        Image.fromarray(arr).save(os.path.join(cls_dir, f"img{i:03d}.png"))
    return n


def main(verbose: bool = True) -> dict:
    log = print if verbose else (lambda *a, **k: None)
    with tempfile.TemporaryDirectory() as root:
        n = _write_image_dir(root, n=96)

        # read the directory tree (readImages counterpart)
        table = read_images(root, recursive=True)
        log(f"read {table.num_rows}/{n} images "
            f"-> dense tensor {table['image'].shape}")
        labels = np.asarray(
            [0.0 if "class0" in p else 1.0 for p in table["path"]])
        table = table.with_column("label", labels)

        # batched transformer ops (the OpenCV stage pipeline)
        transformed = (ImageTransformer(inputCol="image", outputCol="image")
                       .resize(40, 40).center_crop(32, 32).flip()
                       .transform(table))
        assert transformed["image"].shape[1:] == (32, 32, 3)

        # transfer learning via the zoo ConvNet's dense1 features
        repo = create_builtin_repo(os.path.join(root, "zoo"),
                                   include=["ConvNet"])
        dl = ModelDownloader(os.path.join(root, "cache"))
        bundle = dl.load_bundle(dl.download_by_name(repo, "ConvNet"))
        feats = ImageFeaturizer(bundle, inputCol="image",
                                outputCol="features",
                                cutOutputLayers=1).transform(transformed)
        log(f"featurized: {feats['features'].shape}")

        train = feats.slice(0, 72)
        test = feats.slice(72, feats.num_rows)
        model = TrainClassifier(LogisticRegression(), labelCol="label").fit(
            train.drop("image", "path"))
        metrics = ComputeModelStatistics().transform(
            model.transform(test.drop("image", "path")))
        acc = float(metrics["accuracy"][0])
        log(f"transfer-learning accuracy: {acc:.3f}")
        return {"n_images": table.num_rows, "accuracy": acc,
                "feature_dim": feats["features"].shape[1]}


if __name__ == "__main__":
    main()
