"""103 - Before and After: manual pipeline vs auto-ML.

Mirrors the reference's notebook 103 (`notebooks/samples/103 - Before and
After MMLSpark.ipynb`): the same classification task done twice — first the
"before" way with explicit stages (type conversion, categorical encoding,
manual featurization, a bare learner), then the "after" way as one
TrainClassifier whose implicit featurization handles all of it.
"""

import numpy as np

from mmlspark_tpu.core.schema import make_categorical
from mmlspark_tpu.feature import AssembleFeatures
from mmlspark_tpu.ml import ComputeModelStatistics, LogisticRegression, TrainClassifier
from mmlspark_tpu.stages import DataConversion, SelectColumns
from mmlspark_tpu.utils.demo_data import adult_census_like


def main(verbose: bool = True) -> dict:
    log = print if verbose else (lambda *a, **k: None)
    data = adult_census_like(n=600, seed=0)
    n_train = 450
    train = data.slice(0, n_train)
    test = data.slice(n_train, data.num_rows)

    # ---- BEFORE: every step by hand -----------------------------------
    def manual_prepare(t):
        t = SelectColumns(cols=["age", "hours_per_week", "education",
                                "workclass", "income"]).transform(t)
        t = DataConversion(cols=["age", "hours_per_week"],
                           convertTo="double").transform(t)
        t = make_categorical(t, "education")
        t = make_categorical(t, "workclass")
        return t

    prep_train = manual_prepare(train)
    label_idx = make_categorical(prep_train, "income")
    assembler = AssembleFeatures(
        columnsToFeaturize=["age", "hours_per_week", "education",
                            "workclass"]).fit(label_idx)
    feat_train = assembler.transform(label_idx)
    lr = LogisticRegression(featuresCol="features", labelCol="income")
    manual_model = lr.fit(feat_train)

    feat_test = assembler.transform(make_categorical(
        manual_prepare(test), "income",
        levels=label_idx.meta("income").categorical.levels))
    manual_pred = manual_model.transform(feat_test)
    manual_acc = float(np.mean(
        manual_pred["prediction"] == np.asarray(feat_test["income"])))
    log(f"BEFORE (manual stages): accuracy={manual_acc:.3f}")

    # ---- AFTER: one estimator -----------------------------------------
    auto_model = TrainClassifier(LogisticRegression(),
                                 labelCol="income").fit(train)
    metrics = ComputeModelStatistics().transform(auto_model.transform(test))
    auto_acc = float(metrics["accuracy"][0])
    log(f"AFTER (TrainClassifier): accuracy={auto_acc:.3f}")
    return {"manual_accuracy": manual_acc, "auto_accuracy": auto_acc}


if __name__ == "__main__":
    main()
