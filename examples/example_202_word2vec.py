"""202 - Book reviews with Word2Vec embeddings.

Mirrors the reference's notebook 202 (`notebooks/samples/202 - Amazon Book
Reviews - Word2Vec.ipynb`): tokenize reviews, fit Word2Vec skip-gram
embeddings, represent each review as its mean word vector, and train a
classifier on the embedded documents.
"""


from mmlspark_tpu.feature import Tokenizer, Word2Vec
from mmlspark_tpu.ml import ComputeModelStatistics, LogisticRegression, TrainClassifier
from mmlspark_tpu.utils.demo_data import book_reviews_like


def main(verbose: bool = True) -> dict:
    log = print if verbose else (lambda *a, **k: None)
    data = book_reviews_like(n=400, seed=2)
    tokens = Tokenizer(inputCol="text", outputCol="tokens").transform(data)

    w2v = Word2Vec(inputCol="tokens", outputCol="embedding",
                   vectorSize=32, windowSize=4, minCount=3,
                   maxIter=3, seed=0).fit(tokens)
    log(f"vocabulary: {len(w2v.vocabulary)} words")
    synonyms = w2v.find_synonyms("great", 3)
    log(f"synonyms of 'great': {[(w, round(s, 3)) for w, s in synonyms]}")

    embedded = w2v.transform(tokens).drop("text", "tokens")
    train = embedded.slice(0, 300)
    test = embedded.slice(300, embedded.num_rows)
    model = TrainClassifier(LogisticRegression(), labelCol="rating").fit(train)
    metrics = ComputeModelStatistics().transform(model.transform(test))
    out = {c: float(metrics[c][0]) for c in metrics.columns}
    log(f"test metrics: { {k: round(v, 4) for k, v in out.items()} }")
    out["n_vocab"] = len(w2v.vocabulary)
    out["top_synonym"] = synonyms[0][0]
    return out


if __name__ == "__main__":
    main()
