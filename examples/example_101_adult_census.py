"""101 - Adult Census Income Training.

Mirrors the reference's notebook 101 (`notebooks/samples/101 - Adult Census
Income Training.ipynb`): train classifiers over a mixed-type census-like
table with `TrainClassifier` doing all featurization implicitly, compare
every learner family with `FindBestModel`, and evaluate the winner with
`ComputeModelStatistics`.  Runs on a deterministic synthetic census
(utils/demo_data.py) because this build is air-gapped.
"""


from mmlspark_tpu.ml import (
    ComputeModelStatistics,
    DecisionTreeClassifier,
    FindBestModel,
    GBTClassifier,
    LogisticRegression,
    MultilayerPerceptronClassifier,
    NaiveBayes,
    RandomForestClassifier,
    TrainClassifier,
)
from mmlspark_tpu.utils.demo_data import adult_census_like


def main(verbose: bool = True) -> dict:
    log = print if verbose else (lambda *a, **k: None)
    data = adult_census_like(n=600, seed=0)
    n_train = 450
    train = data.slice(0, n_train)
    test = data.slice(n_train, data.num_rows)
    log(f"census-like data: {data.num_rows} rows, "
        f"columns {list(data.columns)}")

    # every learner family of the reference grid
    # (TrainClassifier.scala:74-110)
    learners = {
        "LogisticRegression": LogisticRegression(),
        "DecisionTree": DecisionTreeClassifier(maxDepth=5),
        "RandomForest": RandomForestClassifier(numTrees=10, maxDepth=5),
        "GBT": GBTClassifier(maxIter=10, maxDepth=4),
        "NaiveBayes": NaiveBayes(),
        "MLP": MultilayerPerceptronClassifier(layers=[-1, 32, -1],
                                              maxIter=40),
    }
    models = {name: TrainClassifier(learner, labelCol="income").fit(train)
              for name, learner in learners.items()}

    best = FindBestModel(list(models.values()),
                         evaluationMetric="accuracy").fit(test)
    comparison = best.get_all_model_metrics()
    log("model comparison (test accuracy):")
    for i in range(len(comparison["model_name"])):
        log(f"  {comparison['model_name'][i]}: "
            f"{float(comparison['accuracy'][i]):.3f}")

    scored = best.transform(test)
    result = ComputeModelStatistics().evaluate(scored)
    metrics = {c: float(result.metrics[c][0]) for c in result.metrics.columns}
    log(f"best model metrics: { {k: round(v, 4) for k, v in metrics.items()} }")
    return {
        "accuracies": {name: float(
            ComputeModelStatistics().transform(m.transform(test))["accuracy"][0])
            for name, m in models.items()},
        "best_metrics": metrics,
        "confusion_matrix": result.confusion_matrix,
    }


if __name__ == "__main__":
    main()
