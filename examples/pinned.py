"""Pinned-metric contract for the example workloads.

The learner grid pins exact metrics (tests/benchmark_metrics.csv); the
examples historically only asserted loose thresholds, so a silent quality
drift inside any example's model/featurization stayed invisible (round-2
verdict weak #5).  Each extractor below reduces an example's main() result
to the scalar metrics worth pinning; scripts/regen_examples.py writes them
to tests/example_metrics.json and tests/test_examples.py exact-diffs
against it (regenerate DELIBERATELY, review the diff, commit).
"""

from __future__ import annotations

_R = 4  # pinned decimal places: enough to catch drift, robust to fp noise


def _r(v) -> float:
    return round(float(v), _R)


PIN_EXTRACTORS = {
    "example_101_adult_census.py": lambda out: {
        **{f"accuracy_{k}": _r(v) for k, v in out["accuracies"].items()},
        "best_accuracy": _r(out["best_metrics"]["accuracy"]),
    },
    "example_102_flight_delays.py": lambda out: {
        f"r2_{k}": _r(m["R^2"]) for k, m in out["metrics"].items()
    },
    "example_103_before_and_after.py": lambda out: {
        "manual_accuracy": _r(out["manual_accuracy"]),
        "auto_accuracy": _r(out["auto_accuracy"]),
    },
    "example_201_text_featurizer.py": lambda out: {
        "accuracy": _r(out["accuracy"]), "AUC": _r(out["AUC"]),
    },
    "example_202_word2vec.py": lambda out: {
        "accuracy": _r(out["accuracy"]), "n_vocab": int(out["n_vocab"]),
    },
    "example_301_cifar_eval.py": lambda out: {
        "accuracy": _r(out["accuracy"]),
        "n_test": int(out["n_test"]),
    },
    "example_302_image_pipeline.py": lambda out: {
        "accuracy": _r(out["accuracy"]),
        "feature_dim": int(out["feature_dim"]),
    },
    "example_401_lm_generation.py": lambda out: {
        "final_loss": _r(out["final_loss"]),
        "continuation_accuracy": _r(out["continuation_accuracy"]),
        "n_generated": int(out["n_generated"]),
    },
}


def collect(name: str, out: dict) -> dict:
    return PIN_EXTRACTORS[name](out)
