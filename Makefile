# Developer entry points (the reference's `runme` + sbt targets,
# tools/runme/runme.sh:30-52 + src/project/build.scala).
.PHONY: check check-full test test-full lint bench bench-smoke bench-history chaos-drill serve-drill router-drill data-drill disagg-drill trace-drill tpu-floors install docs notebooks clean

check:            ## full gate: syntax + lint + suite + dryrun + bench smoke
	bash scripts/check.sh

test:             ## CPU-mesh test suite, fast tier (deselects `slow`)
	python -m pytest tests/ -q

test-full:        ## the WHOLE suite incl. slow compile-bound parity tests
	python -m pytest tests/ -q -m ""

check-full:       ## full gate with the whole suite
	bash scripts/check.sh --full

lint:             ## AST lint (unused imports, bare except, tabs)
	python scripts/lint.py

bench:            ## full benchmark on the available backend
	python bench.py

bench-smoke:      ## lint + tiny-size bench incl. quantized + telemetry-overhead arms (JSON contract check, no TPU needed) + history regression check vs the committed baseline
	python scripts/lint.py
	python bench.py --smoke | tee /tmp/mmlspark_tpu_bench_smoke.json
	python -m mmlspark_tpu.observe.history check /tmp/mmlspark_tpu_bench_smoke.json --store tests/bench_history_smoke.jsonl

bench-history:    ## append a full bench run to the local history store and print verdicts
	python bench.py | tee /tmp/mmlspark_tpu_bench.json
	python -m mmlspark_tpu.observe.history ingest /tmp/mmlspark_tpu_bench.json

chaos-drill:      ## run the multi-fault chaos scenario suite end-to-end (NaN rollback, torn rotation, hung step, budget exhaustion)
	python scripts/chaos_drill.py

serve-drill:      ## serving chaos scenarios: burst shed, hung client, poison request, mid-flight SIGTERM drain (scripts/serve_drill.py)
	python scripts/serve_drill.py

router-drill:     ## replica chaos scenarios: crash failover, hang ejection, retry-budget shed, flap re-admission (scripts/router_drill.py)
	python scripts/router_drill.py

data-drill:       ## data-service chaos scenarios: worker crash re-dispatch, dynamic exactly-once, slow-worker load shift, fleet respawn (scripts/data_drill.py)
	python scripts/data_drill.py

disagg-drill:     ## disaggregated-tier chaos scenarios: prefill-burst interference, torn/stalled/crashed KV handoff, prefill-tier drain (scripts/disagg_drill.py)
	python scripts/disagg_drill.py

trace-drill:      ## distributed-tracing drill: one trace id across a crash-mid-handoff failover, waterfall shows both attempts, SLO counts one request (scripts/trace_drill.py)
	python scripts/trace_drill.py

tpu-floors:       ## throughput/MFU floors on a real TPU chip
	MMLSPARK_TPU_TEST_PLATFORM=tpu python -m pytest tests/test_perf_floor.py -q

install:          ## editable install of the package
	pip install -e . --no-deps --no-build-isolation

docs:             ## regenerate generated API docs (gated by test_api_doc_in_sync)
	python -c "from mmlspark_tpu.utils import api_summary; open('docs/api.md','w').write(api_summary())"

notebooks:        ## regenerate notebooks/ from examples/ (gated by test_notebooks)
	python scripts/make_notebooks.py

clean:
	rm -rf build dist *.egg-info .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +
